"""graftlint static-analysis suite tests (tools/graftlint — ISSUE 3).

Pins four guarantees:

1. **Per-rule fixtures**: each of G001–G005 fires on its known-bad snippet
   with exact rule ids and line numbers, and stays silent on the known-good
   twin (``tests/fixtures/graftlint/``).
2. **Suppression machinery**: inline ``# graftlint: disable=G00X`` pragmas
   and the repo-root-anchored baseline round-trip (write → reload → clean).
3. **Tier-1 gate**: the shipped tree (`fedml_tpu/`) has ZERO non-baselined
   findings — any regression that reintroduces a host sync, donation bug,
   recompile hazard or unguarded cross-thread write fails this test.
4. **Runtime purity**: ``jax.make_jaxpr`` tracing of the fused round core is
   effect-free and deterministic; the checker catches effectful/printing/
   nondeterministic functions.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from tools.graftlint import baseline as baseline_mod  # noqa: E402
from tools.graftlint.analyzer import analyze_paths  # noqa: E402

FIXTURES = os.path.join(REPO_ROOT, "tests", "fixtures", "graftlint")


def _findings(*names):
    paths = [os.path.join(FIXTURES, n) for n in names]
    return analyze_paths(paths, repo_root=REPO_ROOT)


def _rule_lines(findings, rule):
    return sorted(f.line for f in findings if f.rule == rule)


class TestRuleFixtures:
    """Exact rule ids + line numbers on known-bad, silence on known-good."""

    def test_g001_bad(self):
        fs = _findings("g001_bad.py")
        assert {f.rule for f in fs} == {"G001"}
        assert _rule_lines(fs, "G001") == [11, 12, 13, 14, 20]

    def test_g001_good(self):
        assert _findings("g001_good.py") == []

    def test_g002_bad(self):
        fs = _findings("g002_bad.py")
        assert {f.rule for f in fs} == {"G002"}
        assert _rule_lines(fs, "G002") == [16, 26]

    def test_g002_good(self):
        assert _findings("g002_good.py") == []

    def test_g003_bad(self):
        fs = _findings("g003_bad.py")
        assert {f.rule for f in fs} == {"G003"}
        assert _rule_lines(fs, "G003") == [15, 19, 23]

    def test_g003_good(self):
        assert _findings("g003_good.py") == []

    def test_g004_bad(self):
        fs = _findings("g004_bad.py")
        assert {f.rule for f in fs} == {"G004"}
        assert _rule_lines(fs, "G004") == [14, 15, 16]

    def test_g004_good(self):
        assert _findings("g004_good.py") == []

    def test_g005_bad(self):
        fs = _findings("g005_bad.py")
        assert {f.rule for f in fs} == {"G005"}
        lines = _rule_lines(fs, "G005")
        # instance-attr conflicts report at the main-side write; the RMW
        # sub-rule reports at the module-state write
        assert 17 in lines       # self._running main-side write
        assert 32 in lines       # Registry.ema read-modify-write
        assert len(lines) == 3   # + self.results

    def test_g005_good(self):
        assert _findings("g005_good.py") == []

    def test_every_rule_has_a_bad_fixture(self):
        """Acceptance: each of G001–G005 has >= 1 firing known-bad fixture."""
        for rule in ("G001", "G002", "G003", "G004", "G005"):
            fs = _findings(f"{rule.lower()}_bad.py")
            assert any(f.rule == rule for f in fs), rule


class TestSuppression:
    def test_pragma_inline(self):
        fs = _findings("pragma_ok.py")
        assert _rule_lines(fs, "G001") == [8]  # line 9 suppressed by pragma

    def test_pragma_file_level(self):
        """A pragma in the prologue (after the docstring, before code)
        suppresses the listed rules for the whole file."""
        assert _findings("pragma_file.py") == []

    def test_baseline_round_trip(self, tmp_path):
        fs = _findings("g001_bad.py")
        assert fs
        path = str(tmp_path / "baseline.json")
        baseline_mod.save(path, fs)
        new, old = baseline_mod.split(fs, baseline_mod.load(path))
        assert new == [] and len(old) == len(fs)
        # a NEW finding (different line text) is not swallowed
        import dataclasses

        extra = dataclasses.replace(fs[0], line=999,
                                    line_text="z = float(q)")
        new, old = baseline_mod.split(fs + [extra], baseline_mod.load(path))
        assert [f.line for f in new] == [999]

    def test_baseline_is_repo_root_anchored(self):
        """Finding paths are repo-relative: identical from any cwd."""
        fs = _findings("g001_bad.py")
        assert all(f.path == "tests/fixtures/graftlint/g001_bad.py"
                   for f in fs)
        assert baseline_mod.default_baseline_path(REPO_ROOT) == os.path.join(
            REPO_ROOT, "tools", "graftlint", "baseline.json")


class TestTreeGate:
    """The tier-1 gate: the shipped tree must be clean vs the baseline."""

    def test_fedml_tpu_clean(self):
        findings = analyze_paths([os.path.join(REPO_ROOT, "fedml_tpu")],
                                 repo_root=REPO_ROOT)
        bl = baseline_mod.load(baseline_mod.default_baseline_path(REPO_ROOT))
        new, _old = baseline_mod.split(findings, bl)
        assert new == [], "non-baselined graftlint findings:\n" + "\n".join(
            f.render() for f in new)

    def test_baseline_has_no_dead_entries(self):
        """Every baseline entry (including its count) still matches real
        findings — the baseline shrinks when debt is paid, it never pads.
        A stale excess count would silently swallow a future regression
        that reintroduces the identical source line."""
        from collections import Counter

        findings = analyze_paths([os.path.join(REPO_ROOT, "fedml_tpu")],
                                 repo_root=REPO_ROOT)
        bl = baseline_mod.load(baseline_mod.default_baseline_path(REPO_ROOT))
        live = Counter(f.baseline_key() for f in findings)
        stale = {k: (n, live.get(k, 0)) for k, n in bl.items()
                 if n > live.get(k, 0)}
        assert stale == {}, f"stale baseline (key: budget vs live): {stale}"


class TestCLI:
    def _run(self, *args):
        return subprocess.run(
            [sys.executable, "-m", "tools.graftlint", *args],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
        )

    def test_exit_nonzero_on_bad_fixture(self):
        r = self._run("tests/fixtures/graftlint/g001_bad.py", "--no-baseline")
        assert r.returncode == 1
        assert "G001" in r.stdout

    def test_exit_zero_on_tree_json(self):
        r = self._run("fedml_tpu", "--format", "json")
        assert r.returncode == 0, r.stdout + r.stderr
        payload = json.loads(r.stdout)
        assert payload["findings"] == []
        assert payload["exit_code"] == 0

    def test_select_filter(self):
        r = self._run("tests/fixtures/graftlint/g001_bad.py",
                      "--no-baseline", "--select", "G002")
        assert r.returncode == 0

    def test_list_rules(self):
        r = self._run("--list-rules")
        assert r.returncode == 0
        for rule in ("G001", "G002", "G003", "G004", "G005"):
            assert rule in r.stdout

    def test_fedml_cli_lint_subcommand(self):
        r = subprocess.run(
            [sys.executable, "-m", "fedml_tpu.cli", "lint"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
        )
        assert r.returncode == 0, r.stdout + r.stderr


class TestRuntimePurity:
    def test_pure_function_passes(self):
        import jax.numpy as jnp

        from tools.graftlint.runtime_check import trace_purity_issues

        assert trace_purity_issues(
            lambda x: jnp.sum(x * 2.0), (jnp.ones((4,)),), name="pure"
        ) == []

    def test_print_is_caught(self):
        import jax.numpy as jnp

        from tools.graftlint.runtime_check import trace_purity_issues

        def noisy(x):
            print("tracing!")
            return x * 2

        issues = trace_purity_issues(noisy, (jnp.ones((4,)),), name="noisy")
        assert any("stdout" in i for i in issues)

    def test_effectful_function_is_caught(self):
        import jax
        import jax.numpy as jnp

        from tools.graftlint.runtime_check import trace_purity_issues

        def effectful(x):
            jax.debug.print("x={x}", x=x)
            return x * 2

        issues = trace_purity_issues(effectful, (jnp.ones((4,)),),
                                     name="effectful")
        assert any("effect" in i.lower() or "callback" in i.lower()
                   for i in issues)

    def test_nondeterministic_trace_is_caught(self):
        import numpy as np
        import jax.numpy as jnp

        from tools.graftlint.runtime_check import trace_purity_issues

        def leaky(x):
            return x * np.random.random_sample()  # fresh constant per trace

        issues = trace_purity_issues(leaky, (jnp.ones((4,)),), name="leaky")
        assert any("different jaxprs" in i for i in issues)

    def test_round_engine_certifies_pure(self):
        """The fused round core traces pure for the reference configs."""
        from tools.graftlint.runtime_check import check_round_engine

        findings = check_round_engine(REPO_ROOT)
        assert findings == [], "\n".join(f.render() for f in findings)
