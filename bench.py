"""Headline benchmark — run on real TPU by the driver each round.

Two measurements, one JSON line:

1. **Parrot FedAvg rounds/sec** (BASELINE.json north star #1): 100 simulated
   clients on CIFAR-10-shaped data, ResNet-56, 10 clients/round, 1 local
   epoch. ``vs_baseline`` divides by the *measured* throughput of the
   reference's own single-process torch loop on the same config
   (``tools/measure_ref_baseline.py`` → ``REF_BASELINE.json``). ResNet-56 is
   used on both sides because it is the reference's CIFAR ResNet
   (``model/cv/resnet.py:257`` — it ships no resnet20).

2. **Cheetah tokens/sec/chip + MFU** (north star #2): single-chip pretraining
   of the flagship decoder-only transformer (~490M params: d2048 x 8L, GQA
   16q/4kv — Llama-standard head_dim 128 — seq 2048, bf16, native-GQA splash
   attention with (512, 512) blocks, chunked fused CE; a remat ladder falls
   back only if no-remat doesn't fit). MFU = achieved model FLOPs/s over
   chip peak bf16 FLOPs/s, with model FLOPs per token = 6·N +
   12·L·layers·d_model (PaLM appendix B convention). Three secondary shapes
   ride along, each in its own subprocess: the r2 wide-head hd512 flagship,
   the remat-on rung (d2048 x 24L, full-block remat — the regime every
   7B-class run lives in; no-remat OOMs there), and the MoE flagship
   (8 experts, top-2, MFU on ACTIVE FLOPs).

The headline line is the FedAvg metric (reference-comparable); the Cheetah
numbers ride along as extra keys so every round's BENCH_r{N}.json records
both.

Timing note: under the axon TPU tunnel ``jax.block_until_ready`` returns
without waiting (measured: a chained-matmul loop "finishes" at 58,000
TFLOP/s), so every timed section here syncs by fetching a scalar from the
result — a device->host transfer cannot complete before the computation it
depends on.
"""

from __future__ import annotations

import json
import os
import time

HERE = os.path.dirname(os.path.abspath(__file__))

# peak bf16 FLOPs/s per chip by device kind (public spec sheets)
TPU_PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def _sync(tree) -> float:
    """True device sync: fetch one scalar (block_until_ready is a no-op
    under the axon tunnel)."""
    import jax
    import numpy as np

    leaf = jax.tree.leaves(tree)[0]
    return float(np.asarray(leaf).ravel()[0])


def _ref_rounds_per_sec() -> float | None:
    """Measured reference throughput (tools/measure_ref_baseline.py)."""
    path = os.path.join(HERE, "REF_BASELINE.json")
    try:
        with open(path) as f:
            return float(json.load(f)["ref_rounds_per_sec"])
    except (OSError, KeyError, ValueError):
        return None


def _same_substrate() -> dict:
    """Both-stacks-on-CPU measurement (tools/measure_same_substrate.py):
    the ratio isolating architecture from hardware."""
    path = os.path.join(HERE, "SELF_CPU_BASELINE.json")
    try:
        with open(path) as f:
            d = json.load(f)
        out = {
            "vs_baseline_same_substrate": d.get("same_substrate_ratio"),
            "same_substrate_config": d.get("config"),
        }
        legs = d.get("legs")
        if legs:
            out["same_substrate_legs"] = {
                m: leg.get("same_substrate_ratio") for m, leg in legs.items()
            }
        return out
    except (OSError, ValueError):
        return {"vs_baseline_same_substrate": None}


def bench_fedavg() -> dict:
    import jax

    import fedml_tpu as fedml
    from fedml_tpu import data as data_mod
    from fedml_tpu import models as model_mod
    from fedml_tpu.arguments import Arguments
    from fedml_tpu.simulation.sp_api import FedAvgAPI

    args = Arguments(overrides=dict(
        dataset="cifar10", model="resnet56", client_num_in_total=100,
        client_num_per_round=10, comm_round=12, epochs=1, batch_size=32,
        learning_rate=0.1, frequency_of_the_test=1000,
    ))
    args.train_dtype = "bf16"  # MXU-native compute, fp32 master weights
    args = fedml.init(args, should_init_logs=False)
    ds, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    api = FedAvgAPI(args, fedml.get_device(args), ds, bundle)

    # warmup (compile) — 2 rounds
    for r in range(2):
        args.round_idx = r
        api._train_round(r)
    _sync(api.global_params)

    n_rounds = 10
    t0 = time.perf_counter()
    for r in range(2, 2 + n_rounds):
        args.round_idx = r
        api._train_round(r)
    _sync(api.global_params)
    dt = time.perf_counter() - t0
    return {"rounds_per_sec": n_rounds / dt}


def bench_cheetah() -> dict:
    """Single-chip flagship-transformer pretrain throughput + MFU."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from fedml_tpu.parallel.sharding import make_mesh
    from fedml_tpu.parallel.train_step import CheetahTrainer, make_optimizer
    from fedml_tpu.parallel.transformer import TransformerConfig

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        # The flagship is the PRODUCT shape: Llama-standard head_dim 128
        # with GQA 16q/4kv on a wide-shallow d2048 x 8L body — chosen
        # product-shape-first, not max-MFU-first. Two levers got it to
        # 75.7% MFU on the v5e (tools/mfu_sweep.py):
        # - wide-shallow beats deep-narrow (d2048x8L ~2.1x the MFU of
        #   d1024x24) — bigger matmuls, fewer kernel launches;
        # - native-GQA splash attention (make_splash_mqa — K/V never
        #   repeated to 16 heads) with explicit (512, 512) kernel blocks:
        #   42% -> 75.7% for this shape, past the r2 bench-tuned hd512
        #   flagship's 67%. (With the same block tuning hd512 reaches
        #   79.4% — measured as the secondary datapoint below — but the
        #   headline stays the shape people actually train.)
        base = dict(
            vocab_size=32000, d_model=2048, n_layers=8, n_heads=16,
            n_kv_heads=4, d_ff=5632, max_seq_len=2048,
            attn_block_q=512, attn_block_kv=512,
        )
        # memory/recompute ladder, fastest first (tools/mfu_sweep.py):
        # no-remat needs the most HBM; "dots" saves matmul outputs only;
        # full-block remat always fits
        ladder = [
            dict(remat=False),
            dict(remat=True, remat_policy="dots"),
            dict(remat=True, remat_policy="full"),
        ]
        batch, seq, steps, warmup = 8, 2048, 20, 3
    else:  # CPU smoke config so the bench degrades gracefully off-TPU
        base = dict(
            vocab_size=1024, d_model=256, n_heads=8,
            n_kv_heads=8, d_ff=704, max_seq_len=512, n_layers=4,
        )
        ladder = [dict(remat=False)]
        batch, seq, steps, warmup = 2, 256, 4, 1

    mesh = make_mesh()  # all local devices on the data axis
    rng = np.random.RandomState(0)

    import gc

    state = trainer = cfg = None
    last_err = ""
    for rung in ladder:
        cfg = TransformerConfig(**{**base, **rung})
        trainer = CheetahTrainer(
            cfg, mesh,
            optimizer=make_optimizer(learning_rate=3e-4, warmup_steps=10,
                                     total_steps=steps + warmup,
                                     mu_dtype=jnp.bfloat16),
        )
        try:
            state = trainer.init_state(jax.random.PRNGKey(0))
            mask = jnp.ones((batch, seq), jnp.int32)
            tok = jnp.asarray(
                rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
            )
            state, metrics = trainer.train_step(state, tok, mask)
            _sync(metrics["loss"])
            break  # this rung compiles and fits
        except Exception as e:  # OOM at this rung: drop to more remat
            # keep only the repr — the traceback would pin the OOMed
            # trainer's buffers and poison the next rung's HBM headroom
            last_err = f"{type(e).__name__}: {e}"[:500]
            state = trainer = None
            gc.collect()
    if state is None:
        raise RuntimeError(f"no cheetah config fit on this chip: {last_err}")
    n_params = sum(int(p.size) for p in jax.tree.leaves(state.params))

    def batch_tokens():
        return jnp.asarray(
            rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
        )

    for _ in range(warmup):
        state, metrics = trainer.train_step(state, batch_tokens(), mask)
    _sync(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = trainer.train_step(state, batch_tokens(), mask)
    _sync(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens = steps * batch * seq
    tps = tokens / dt
    # model FLOPs per token (fwd+bwd): 6N matmul + 12·L·layers·d_model attn
    flops_per_token = 6.0 * n_params + 12.0 * seq * cfg.n_layers * cfg.d_model
    achieved = tps * flops_per_token
    kind = jax.devices()[0].device_kind
    peak = TPU_PEAK_FLOPS.get(kind)
    n_chips = jax.device_count()
    out = {
        "cheetah_tokens_per_sec_per_chip": round(tps / n_chips, 1),
        "cheetah_params_m": round(n_params / 1e6, 1),
        "cheetah_seq_len": seq,
        "cheetah_device_kind": kind,
        "cheetah_remat": cfg.remat_policy if cfg.remat else "none",
    }
    if peak:
        out["cheetah_mfu"] = round(achieved / (peak * n_chips), 4)
    return out


def main() -> None:
    # subprocess measurements FIRST — before this process owns the TPU
    extra = {}
    for prefix, fn in (("cheetah_hd512", bench_cheetah_hd512),
                       ("cheetah_remat", bench_cheetah_remat),
                       ("cheetah_moe", bench_cheetah_moe)):
        try:
            extra.update(fn())
        except Exception as e:
            # same key scheme as _mfu_subprocess's non-zero-exit path
            extra[f"{prefix}_error"] = f"{type(e).__name__}: {e}"
    fed = bench_fedavg()
    value = fed["rounds_per_sec"]
    ref = _ref_rounds_per_sec()
    line = {
        "metric": "fedavg_rounds_per_sec_100clients_cifar10_resnet56",
        "value": round(value, 4),
        "unit": "rounds/s",
        # TPU vs the reference's torch CPU (its only substrate here) —
        # conflates hardware with architecture, hence the companion below
        "vs_baseline": round(value / ref, 2) if ref else None,
        "ref_rounds_per_sec_measured": ref,
        # ours-on-CPU / reference-on-CPU: the architectural win alone
        **_same_substrate(),
    }
    try:
        line.update(bench_cheetah())
    except Exception as e:  # cheetah bench must never hide the headline
        line["cheetah_error"] = f"{type(e).__name__}: {e}"
    line.update(extra)
    print(json.dumps(line))


def _mfu_subprocess(cfg: dict, prefix: str) -> dict:
    """One mfu_sweep child measurement → {prefix_mfu, prefix_tok_s}.

    Runs as a SUBPROCESS and must be called BEFORE this process touches the
    TPU: stock libtpu grants exclusive per-process device ownership, so a
    child spawned after the parent initializes jax could never open the
    chip (tools/mfu_sweep.py's parent never imports jax for this reason).
    """
    import subprocess
    import sys

    env = dict(os.environ)
    env["PYTHONPATH"] = HERE + os.pathsep + env.get("PYTHONPATH", "")
    p = subprocess.run(
        [sys.executable, os.path.join(HERE, "tools", "mfu_sweep.py"),
         "--one", json.dumps(cfg)],
        capture_output=True, text=True, timeout=900, env=env,
    )
    out = (p.stdout.strip().splitlines() or ["<no output>"])[-1]
    if p.returncode != 0:
        err = (p.stderr.strip().splitlines() or [""])[-1]
        return {f"{prefix}_error": f"rc={p.returncode} {out[:120]} {err[:200]}"}
    alt = json.loads(out)
    if "skipped" in alt:  # CPU-only host: the child declined the TPU shape
        return {}
    res = {
        f"{prefix}_mfu": alt["mfu"],
        f"{prefix}_tokens_per_sec_per_chip": alt["tok_s"],
    }
    if "params_active_m" in alt:
        res[f"{prefix}_params_active_m"] = alt["params_active_m"]
        res[f"{prefix}_params_total_m"] = alt["params_m"]
    return res


def bench_cheetah_hd512() -> dict:
    """Secondary shape (the r2 wide-head flagship, GQA 4q/2kv hd512) so both
    datapoints stay measured round over round."""
    return _mfu_subprocess(dict(
        vocab_size=32000, d_model=2048, n_layers=8, n_heads=4,
        n_kv_heads=2, d_ff=5632, max_seq_len=2048, remat=False,
        remat_policy="full", attn_impl="auto", batch=8, seq=2048,
        steps=10, loss_chunk=256, mu_bf16=True,
        attn_block_q=512, attn_block_kv=512,  # clamped; 79.4% measured
    ), "cheetah_hd512")


def bench_cheetah_remat() -> dict:
    """The remat-on MFU rung (VERDICT r3 next #3): d2048 x 24L (1.21B — the
    flagship deepened past the no-remat HBM wall; 24L no-remat OOMs at
    bs8/seq2048, measured) with remat_policy="full". This is the regime
    every 7B-class run lives in; the headline's no-remat number says
    nothing about it. "full" (save block inputs only) is the policy that
    wins here — measured, "dots" SAVES every matmul output and needs MORE
    HBM than no-remat once splash attention keeps scores out of HBM
    (16L dots OOMs at 19.5 GiB while 16L no-remat fits in 13)."""
    return _mfu_subprocess(dict(
        vocab_size=32000, d_model=2048, n_layers=24, n_heads=16,
        n_kv_heads=4, d_ff=5632, max_seq_len=2048, remat=True,
        remat_policy="full", attn_impl="auto", batch=8, seq=2048,
        steps=8, loss_chunk=256, mu_bf16=True,
        attn_block_q=512, attn_block_kv=512,
    ), "cheetah_remat")


def bench_cheetah_moe() -> dict:
    """MoE flagship (VERDICT r3 next #4): 8 experts, top-2, scatter/gather
    dispatch (parallel/moe.py). MFU is reported on ACTIVE FLOPs (top_k/E of
    expert FFN params per token — the standard MoE convention)."""
    return _mfu_subprocess(dict(
        vocab_size=32000, d_model=2048, n_layers=4, n_heads=16,
        n_kv_heads=4, d_ff=2816, max_seq_len=2048, remat=True,
        remat_policy="full", attn_impl="auto", batch=8, seq=2048,
        steps=8, loss_chunk=256, mu_bf16=True,
        attn_block_q=512, attn_block_kv=512,
        moe_experts=8, moe_top_k=2, moe_capacity_factor=1.25,
    ), "cheetah_moe")


if __name__ == "__main__":
    main()
