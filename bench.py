"""Headline benchmark — run on real TPU by the driver each round.

Measurements (one cumulative JSON line, re-printed as legs complete):

1. **Parrot FedAvg rounds/sec** (BASELINE.json north star #1): 100 simulated
   clients on CIFAR-10-shaped data, ResNet-56, 10 clients/round, 1 local
   epoch. ``vs_baseline`` divides by the *measured* throughput of the
   reference's own single-process torch loop on the same config
   (``tools/measure_ref_baseline.py`` → ``REF_BASELINE.json``). ResNet-56 is
   used on both sides because it is the reference's CIFAR ResNet
   (``model/cv/resnet.py:257`` — it ships no resnet20).

2. **Cheetah tokens/sec/chip + MFU** (north star #2): single-chip pretraining
   of the flagship decoder-only transformer (~490M params: d2048 x 8L, GQA
   16q/4kv — Llama-standard head_dim 128 — seq 2048, bf16, native-GQA splash
   attention with (512, 512) blocks, chunked fused CE; a remat ladder falls
   back only if no-remat doesn't fit). MFU = achieved model FLOPs/s over
   chip peak bf16 FLOPs/s, with model FLOPs per token = 6·N +
   12·L·layers·d_model (PaLM appendix B convention). Three secondary shapes
   ride along: the r2 wide-head hd512 flagship, the remat-on rung
   (d2048 x 24L, full-block remat — the regime every 7B-class run lives in),
   and the MoE flagship (8 experts, top-2, MFU on ACTIVE FLOPs).

Stall-proofing (round 5 — VERDICT r4 #1; r4 recorded rc=124 and NOTHING):

- The parent process NEVER imports jax. Every measurement runs in its own
  subprocess leg with its own timeout; a wedged tunnel costs one leg, not
  the round.
- After EVERY completed leg the parent re-prints the full cumulative JSON
  line, so an external kill at any moment leaves the most complete line as
  the output tail (the driver parses the tail).
- A global deadline (env ``BENCH_BUDGET_S``, default 2400) skips remaining
  legs with explicit ``"<leg>_skipped": "budget"`` markers instead of dying
  with rc=124.
- Completed TPU legs are checkpointed to ``BENCH_PARTIAL.json`` keyed by a
  digest of the leg config + the source files that produce the number; a
  later run reuses any matching row younger than ``BENCH_CACHE_TTL_S``
  (default 7 days). A bench run earlier in the round therefore insures the
  driver's end-of-round run against a slow tunnel: cached legs are merged
  in milliseconds and marked ``"<leg>_cached": true``.

Timing note: under the axon TPU tunnel ``jax.block_until_ready`` returns
without waiting (measured: a chained-matmul loop "finishes" at 58,000
TFLOP/s), so every timed section here syncs by fetching a scalar from the
result — a device->host transfer cannot complete before the computation it
depends on.
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
PARTIAL_PATH = os.path.join(HERE, "BENCH_PARTIAL.json")

# peak bf16 FLOPs/s per chip by device kind (public spec sheets)
TPU_PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}

# ---------------------------------------------------------------------------
# Leg configs — module-level so the parent can hash them without importing
# jax and the children run exactly what was hashed.
# ---------------------------------------------------------------------------

FEDAVG_OVERRIDES = dict(
    dataset="cifar10", model="resnet56", client_num_in_total=100,
    client_num_per_round=10, comm_round=12, epochs=1, batch_size=32,
    learning_rate=0.1, frequency_of_the_test=1000,
)

# Million-client cohort leg (fedml_tpu/scale/ — ROADMAP "Million-client
# simulation substrate"): N registered clients in a packed registry,
# 10k-client cohorts sampled K-of-N on device, shards streamed through the
# double-buffered prefetcher. Deliberately CPU-runnable (lr on synthetic
# shapes): the leg measures the SUBSTRATE — rounds/s at population scale,
# prefetch overlap fraction, and zero cohort-driven recompiles in steady
# state — not model FLOPs. BENCH_REGISTRY_N / BENCH_COHORT_K scale it down
# for smoke runs.
MILLION_OVERRIDES = dict(
    dataset="synthetic", model="lr", client_num_in_total=64,
    client_num_per_round=16, comm_round=16, epochs=1, batch_size=8,
    learning_rate=0.05, frequency_of_the_test=1000,
)
MILLION_REGISTRY_N = 1_000_000
MILLION_COHORT_K = 10_000

# Delta-delivery leg (fedml_tpu/delivery/ — ISSUE 9, docs/delivery.md):
# the SAME cross-silo federation twice — full pytrees vs the delta plane
# (EF-top-k C2S deltas decoded against the version store + lossless sparse
# S2C delta frames) — and reports steady-state comm bytes per round for
# both, the reduction factor, and accuracy at parity. mnist-lr is the
# deliberate shape: big enough (7,850 params, ~31 KB/frame) that frame
# headers don't dominate, small enough to run in seconds on a CPU host.
COMPRESSED_OVERRIDES = dict(
    training_type="cross_silo", dataset="mnist", model="lr",
    client_num_in_total=4, client_num_per_round=4, epochs=1, batch_size=32,
    learning_rate=0.05, backend="LOOPBACK", frequency_of_the_test=1,
    random_seed=0,
)
COMPRESSED_SCHEME = dict(compression="eftopk", compression_ratio=0.01)

# Device-direct wire leg (fedml_tpu/delivery/device_codec.py — docs/
# delivery.md "Device-direct wire path"): host-CPU cost of putting one S2C
# frame on the wire, full vs host-delta vs device-delta, at a frame size
# where per-call overhead vanishes (~16 MB fp32). "Host CPU" is SERVING-
# THREAD CPU time (``time.thread_time``): the resource the device path
# frees — jit'd kernels run off the serving thread (off-host entirely on
# TPU; on the CPU backend they land in XLA's pool, so wall time there is
# a stand-in, flagged by ``platform``). The parity gate is absolute: the
# device frames must be byte-identical to the host codec's before any
# timing is believed. BENCH_WIRE_DIM / BENCH_WIRE_REPS scale it down for
# smoke runs.
WIRE_DIM = 4_000_000
WIRE_CHANGED_FRAC = 0.01  # steady-state sparse-ish round delta
WIRE_SOAK = dict(clients=8, steps=3, think_s=0.01, seed=7)

# The flagship is the PRODUCT shape: Llama-standard head_dim 128 with GQA
# 16q/4kv on a wide-shallow d2048 x 8L body — chosen product-shape-first,
# not max-MFU-first. Two levers got it to 75.7% MFU on the v5e
# (tools/mfu_sweep.py): wide-shallow beats deep-narrow (~2.1x the MFU of
# d1024x24), and native-GQA splash attention (make_splash_mqa — K/V never
# repeated to 16 heads) with explicit (512, 512) kernel blocks: 42% -> 75.7%.
CHEETAH_BASE = dict(
    vocab_size=32000, d_model=2048, n_layers=8, n_heads=16,
    n_kv_heads=4, d_ff=5632, max_seq_len=2048,
    attn_block_q=512, attn_block_kv=512,
)
# memory/recompute ladder, fastest first: no-remat needs the most HBM;
# "dots" saves matmul outputs only; full-block remat always fits
CHEETAH_LADDER = [
    dict(remat=False),
    dict(remat=True, remat_policy="dots"),
    dict(remat=True, remat_policy="full"),
]
CHEETAH_RUN = dict(batch=8, seq=2048, steps=20, warmup=3)

HD512_CFG = dict(
    vocab_size=32000, d_model=2048, n_layers=8, n_heads=4,
    n_kv_heads=2, d_ff=5632, max_seq_len=2048, remat=False,
    remat_policy="full", attn_impl="auto", batch=8, seq=2048,
    steps=10, loss_chunk=256, mu_bf16=True,
    attn_block_q=512, attn_block_kv=512,  # clamped; 79.4% measured
)

# The remat-on MFU rung: d2048 x 24L (1.21B — the flagship deepened past the
# no-remat HBM wall; 24L no-remat OOMs at bs8/seq2048, measured) with
# remat_policy="full". "full" (save block inputs only) wins here — measured,
# "dots" SAVES every matmul output and needs MORE HBM than no-remat once
# splash attention keeps scores out of HBM.
REMAT_CFG = dict(
    vocab_size=32000, d_model=2048, n_layers=24, n_heads=16,
    n_kv_heads=4, d_ff=5632, max_seq_len=2048, remat=True,
    remat_policy="full", attn_impl="auto", batch=8, seq=2048,
    steps=8, loss_chunk=256, mu_bf16=True,
    attn_block_q=512, attn_block_kv=512,
)

# MoE flagship: 8 experts, top-2, sort-based grouped dispatch
# (parallel/moe.py). MFU is reported on ACTIVE FLOPs (top_k/E of expert FFN
# params per token — the standard MoE convention).
MOE_CFG = dict(
    vocab_size=32000, d_model=2048, n_layers=4, n_heads=16,
    n_kv_heads=4, d_ff=2816, max_seq_len=2048, remat=True,
    remat_policy="full", attn_impl="auto", batch=8, seq=2048,
    steps=8, loss_chunk=256, mu_bf16=True,
    attn_block_q=512, attn_block_kv=512,
    moe_experts=8, moe_top_k=2, moe_capacity_factor=1.25,
)

# source files whose content feeds each leg's cache digest: editing the
# engine invalidates the cached number
_CHEETAH_SOURCES = [
    "fedml_tpu/parallel/transformer.py", "fedml_tpu/parallel/train_step.py",
    "fedml_tpu/parallel/sharding.py", "fedml_tpu/parallel/ring_attention.py",
    "fedml_tpu/parallel/moe.py", "tools/mfu_sweep.py", "bench.py",
]
_FEDAVG_SOURCES = [
    "fedml_tpu/simulation/sp_api.py", "fedml_tpu/simulation/round_engine.py",
    "fedml_tpu/ml/local_train.py", "fedml_tpu/core/mlops/telemetry.py",
    "fedml_tpu/models/vision.py", "fedml_tpu/data/datasets.py", "bench.py",
]
_MILLION_SOURCES = [
    "fedml_tpu/scale/registry.py", "fedml_tpu/scale/cohort_engine.py",
    "fedml_tpu/scale/prefetch.py", "fedml_tpu/simulation/sp_api.py",
    "fedml_tpu/simulation/round_engine.py", "bench.py",
]
_COMPRESSED_SOURCES = [
    "fedml_tpu/delivery/model_store.py", "fedml_tpu/delivery/delta_codec.py",
    "fedml_tpu/delivery/device_codec.py", "fedml_tpu/core/compression.py",
    "fedml_tpu/cross_silo/server_manager.py",
    "fedml_tpu/cross_silo/client_manager.py",
    "fedml_tpu/core/distributed/message.py", "bench.py",
]
_WIRE_SOURCES = [
    "fedml_tpu/delivery/device_codec.py", "fedml_tpu/delivery/delta_codec.py",
    "fedml_tpu/delivery/model_store.py",
    "fedml_tpu/core/distributed/tensor_transport.py",
    "fedml_tpu/traffic/swarm.py", "bench.py",
]


def _sync(tree) -> float:
    """True device sync: fetch one scalar (block_until_ready is a no-op
    under the axon tunnel)."""
    import numpy as np

    import jax

    leaf = jax.tree.leaves(tree)[0]
    return float(np.asarray(leaf).ravel()[0])


def _ref_rounds_per_sec() -> float | None:
    """Measured reference throughput (tools/measure_ref_baseline.py)."""
    path = os.path.join(HERE, "REF_BASELINE.json")
    try:
        with open(path) as f:
            return float(json.load(f)["ref_rounds_per_sec"])
    except (OSError, KeyError, ValueError):
        return None


def _same_substrate() -> dict:
    """Both-stacks-on-CPU measurement (tools/measure_same_substrate.py):
    the ratio isolating architecture from hardware."""
    path = os.path.join(HERE, "SELF_CPU_BASELINE.json")
    try:
        with open(path) as f:
            d = json.load(f)
        out = {
            "vs_baseline_same_substrate": d.get("same_substrate_ratio"),
            "same_substrate_config": d.get("config"),
        }
        legs = d.get("legs")
        if legs:
            out["same_substrate_legs"] = {
                m: leg.get("same_substrate_ratio") for m, leg in legs.items()
            }
        return out
    except (OSError, ValueError):
        return {"vs_baseline_same_substrate": None}


# ---------------------------------------------------------------------------
# Leg children (run in subprocesses; print one JSON line on stdout)
# ---------------------------------------------------------------------------


def _maybe_force_platform() -> None:
    """Honor ``BENCH_PLATFORM=cpu`` for off-TPU driving. The environment pins
    ``JAX_PLATFORMS=axon`` via sitecustomize and IGNORES the env var, so the
    only working override is ``jax.config`` before first backend touch —
    without this, a "CPU" leg actually dials the axon tunnel and inherits
    its stalls."""
    plat = os.environ.get("BENCH_PLATFORM")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)


def bench_fedavg() -> dict:
    """Headline FedAvg leg, on the fused round engine (round_engine.py).

    Reports the compile wall SEPARATELY from steady-state throughput:
    ``fedavg_compile_s`` is the first-round wall time (lowering + XLA compile
    + the round itself), ``rounds_per_sec`` is measured over post-warmup
    rounds only. The persistent XLA compilation cache is enabled (env
    ``BENCH_COMPILE_CACHE_DIR``), so repeat runs — and the driver's
    end-of-round run after an earlier insurance run — skip the compile wall
    and ``fedavg_compile_s`` collapses to deserialization time.
    """
    _maybe_force_platform()
    import jax

    import fedml_tpu as fedml
    from fedml_tpu import data as data_mod
    from fedml_tpu import models as model_mod
    from fedml_tpu.arguments import Arguments
    from fedml_tpu.core.mlops import telemetry
    from fedml_tpu.simulation.sp_api import FedAvgAPI

    # count compiles + compilation-cache hits from the very first jit, so
    # the telemetry the leg reports covers the compile wall too
    telemetry.install_jax_listeners()

    platform = jax.devices()[0].platform
    if platform == "tpu":
        overrides = dict(FEDAVG_OVERRIDES)
        n_rounds, warmup = 10, 2
    elif os.environ.get("BENCH_SMOKE"):
        # harness smoke (tools/bench_smoke.sh): a seconds-scale synthetic
        # 2-round leg proving the orchestrator never regresses to rc=124
        overrides = dict(
            dataset="synthetic", model="lr", client_num_in_total=8,
            client_num_per_round=4, comm_round=3, epochs=1, batch_size=16,
            learning_rate=0.03, frequency_of_the_test=1000,
        )
        n_rounds, warmup = 2, 1
    else:
        # XLA:CPU lowers the vmapped ResNet grouped-conv path pathologically
        # (>60 min compiles — SELF_CPU_BASELINE.json); off-TPU the leg runs a
        # seconds-scale LR smoke so the bench degrades instead of wedging.
        # The parent marks it and suppresses vs_baseline (different config).
        overrides = dict(
            dataset="mnist", model="lr", client_num_in_total=10,
            client_num_per_round=4, comm_round=6, epochs=1, batch_size=32,
            learning_rate=0.03, frequency_of_the_test=1000,
        )
        n_rounds, warmup = 4, 1
    args = Arguments(overrides=overrides)
    args.train_dtype = "bf16"  # MXU-native compute, fp32 master weights
    from fedml_tpu.constants import BENCH_COMPILE_CACHE_DIR_DEFAULT

    args.compilation_cache_dir = os.environ.get(
        "BENCH_COMPILE_CACHE_DIR", BENCH_COMPILE_CACHE_DIR_DEFAULT
    )
    # superround: n_rounds rounds per device-program launch (lax.scan with
    # on-device client sampling) — steady state is bounded by device
    # compute, not Python dispatch. Falls back to per-round launches on
    # configs that can't scan (run_rounds handles both).
    args.superround_k = n_rounds
    args = fedml.init(args, should_init_logs=False)
    ds, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    api = FedAvgAPI(args, fedml.get_device(args), ds, bundle)

    t0 = time.perf_counter()
    args.round_idx = 0
    api.run_rounds(0, n_rounds)  # compile wall + first launch
    _sync(api.global_params)  # global params depend on every round in flight
    compile_s = time.perf_counter() - t0
    for w in range(1, warmup):
        api.run_rounds(w * n_rounds, n_rounds)
    _sync(api.global_params)

    t0 = time.perf_counter()
    api.run_rounds(warmup * n_rounds, n_rounds)
    _sync(api.global_params)
    dt = time.perf_counter() - t0

    # tracked pass (telemetry plane): runs AFTER the timed window so
    # tracking can never tax the steady-state number. One RoundRecord per
    # round supplies the per-phase breakdown BENCH_*.json carries; the
    # JSONL log + metrics exposition land in BENCH_TRACKING_DIR when set
    # (tools/bench_smoke.sh asserts both parse), a temp dir otherwise.
    import tempfile

    from fedml_tpu.core import mlops

    track_dir = (os.environ.get("BENCH_TRACKING_DIR")
                 or tempfile.mkdtemp(prefix="fedml_bench_track_"))
    args.enable_tracking = True
    args.tracking_dir = track_dir
    # pid-unique run id: a persistent BENCH_TRACKING_DIR must not append
    # this run's records onto a previous run's JSONL (read_events would
    # then sum stale rounds into the phase breakdown)
    args.run_id = f"bench_fedavg_{os.getpid()}"
    args.metrics_file = os.path.join(track_dir, "metrics.prom")
    mlops.init(args)
    t0 = time.perf_counter()
    api.run_rounds((warmup + 1) * n_rounds, n_rounds)
    tracked_wall = time.perf_counter() - t0
    phases, n_records = mlops.phase_totals(mlops.read_events())
    counters = telemetry.registry().snapshot()["counters"]
    mlops.close()  # emits the telemetry summary + forces the metrics file

    # optional resume-overhead probe (BENCH_RESUME=1; on by default in the
    # smoke config): train a short checkpointed run, then measure the time
    # from "process restart" (fresh engine construction) to the first
    # post-resume round DISPATCH — the number that makes checkpoint-cadence
    # tuning data-driven (core/runstate.py resume path)
    resume_overhead_s = None
    want_resume = os.environ.get(
        "BENCH_RESUME", "1" if os.environ.get("BENCH_SMOKE") else "0"
    ) == "1"
    if want_resume:
        from fedml_tpu.checkpoint import CheckpointManager

        import shutil

        ckpt_dir = tempfile.mkdtemp(prefix="fedml_bench_resume_")
        try:
            # preempt_signals=False: the probe must not install the
            # process-wide SIGTERM/SIGINT latch — the operator's Ctrl-C has
            # to keep killing the remaining bench legs
            args_r = Arguments(overrides=dict(
                overrides, checkpoint_dir=ckpt_dir, checkpoint_rounds=1,
                comm_round=2, superround_k=0, preempt_signals=False,
            ))
            args_r.compilation_cache_dir = args.compilation_cache_dir
            args_r = fedml.init(args_r, should_init_logs=False)
            FedAvgAPI(args_r, fedml.get_device(args_r), ds, bundle).train()
            t0 = time.perf_counter()
            api_r = FedAvgAPI(args_r, fedml.get_device(args_r), ds, bundle)
            ckpt_r = CheckpointManager(ckpt_dir)
            start = api_r._maybe_resume(ckpt_r)
            args_r.round_idx = start
            api_r.run_round(start)  # returns at dispatch, not at ready
            resume_overhead_s = time.perf_counter() - t0
            ckpt_r.close()
        finally:
            shutil.rmtree(ckpt_dir, ignore_errors=True)

    return {
        **({"fedavg_resume_overhead_s": round(resume_overhead_s, 4)}
           if resume_overhead_s is not None else {}),
        "rounds_per_sec": n_rounds / dt,
        "fedavg_compile_s": round(compile_s, 3),
        "fedavg_round_fused": api._round_step is not None,
        "fedavg_superround_k": api._superround_k or 0,
        "fedavg_phases": {k: round(v, 4) for k, v in phases.items()},
        "fedavg_phase_rounds": n_records,
        "fedavg_tracked_wall_s": round(tracked_wall, 4),
        "fedavg_compile_cache_hits": int(
            counters.get("jax.compilation_cache.hits", 0)),
        "fedavg_compile_cache_misses": int(
            counters.get("jax.compilation_cache.misses", 0)),
        "platform": platform,
        "device_kind": jax.devices()[0].device_kind,
    }


def bench_million_client() -> dict:
    """FedAvg over a million-client registry with 10k-client streamed
    cohorts (fedml_tpu/scale/). Headline numbers:

    - ``million_rounds_per_sec`` — steady-state rounds/s with N registered
      clients and K-client cohorts streaming through the prefetcher;
    - ``million_prefetch_overlap`` — fraction of shard-gather time hidden
      behind device compute over the measured window (>0 required: the
      pipeline must actually overlap, not serialize);
    - ``million_steady_compiles`` — XLA compiles during the measured
      window (must be 0: cohort resampling every round is recompile-free
      by construction — pad-to-bucket static shapes + jit'd K-of-N
      sampling with a traced round index).
    """
    _maybe_force_platform()
    import numpy as np  # noqa: F401  (jax init ordering)

    import jax

    import fedml_tpu as fedml
    from fedml_tpu import data as data_mod
    from fedml_tpu import models as model_mod
    from fedml_tpu.arguments import Arguments
    from fedml_tpu.constants import BENCH_COMPILE_CACHE_DIR_DEFAULT
    from fedml_tpu.core.mlops import telemetry
    from fedml_tpu.simulation.sp_api import FedAvgAPI

    # count compiles from the very first jit so the steady-state window's
    # delta is trustworthy
    telemetry.install_jax_listeners()

    n = int(os.environ.get("BENCH_REGISTRY_N", MILLION_REGISTRY_N))
    k = int(os.environ.get("BENCH_COHORT_K", MILLION_COHORT_K))
    warmup, measured = 2, 6
    args = Arguments(overrides=dict(
        MILLION_OVERRIDES, client_registry=str(n), cohort_size=k,
        cohort_prefetch=1,
    ))
    args.compilation_cache_dir = os.environ.get(
        "BENCH_COMPILE_CACHE_DIR", BENCH_COMPILE_CACHE_DIR_DEFAULT
    )
    args = fedml.init(args, should_init_logs=False)
    ds, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    api = FedAvgAPI(args, fedml.get_device(args), ds, bundle)

    t0 = time.perf_counter()
    args.round_idx = 0
    for r in range(warmup):
        api.run_round(r)
    _sync(api.global_params)
    compile_s = time.perf_counter() - t0

    reg = telemetry.registry()
    compiles0 = reg.counter("jax.compiles")
    pf0 = api.cohort_engine.stats()
    t0 = time.perf_counter()
    for r in range(warmup, warmup + measured):
        api.run_round(r)
    _sync(api.global_params)
    dt = time.perf_counter() - t0
    steady_compiles = reg.counter("jax.compiles") - compiles0
    pf1 = api.cohort_engine.stats()
    api.cohort_engine.close()

    win_gather = pf1["gather_s"] - pf0["gather_s"]
    win_wait = pf1["wait_s"] - pf0["wait_s"]
    overlap = (
        max(0.0, min(1.0, 1.0 - win_wait / win_gather))
        if win_gather > 1e-12 else 0.0
    )
    return {
        "million_rounds_per_sec": round(measured / dt, 4),
        "million_registry_n": n,
        "million_cohort_k": k,
        "million_prefetch_overlap": round(overlap, 4),
        "million_prefetch_gather_s": round(win_gather, 4),
        "million_prefetch_wait_s": round(win_wait, 4),
        "million_steady_compiles": int(steady_compiles),
        "million_compile_s": round(compile_s, 3),
        "million_round_fused": api._round_step is not None,
        "platform": jax.devices()[0].platform,
        "device_kind": jax.devices()[0].device_kind,
    }


def bench_compressed_round() -> dict:
    """Delta-delivery leg (ISSUE 9): steady-state ``comm.bytes`` per round,
    full pytrees vs the delta plane, at parity accuracy.

    Per-round bytes are measured MARGINALLY — each config runs a short and
    a long federation and reports ``(bytes_long − bytes_short) / Δrounds``
    — so the INIT/FINISH full-model frames (identical in both configs)
    cancel instead of diluting the reduction factor. The acceptance gate
    (``tools/bench_smoke.sh``): the delta path engages (S2C delta frames +
    C2S delta decodes both nonzero) and bytes drop ≥10x with final
    accuracy within 0.05 of the uncompressed run.
    """
    _maybe_force_platform()
    import threading

    import jax

    import fedml_tpu as fedml
    from fedml_tpu import data as data_mod
    from fedml_tpu import models as model_mod
    from fedml_tpu.arguments import Arguments
    from fedml_tpu.core.mlops import telemetry

    def run_world(run_id, rounds, extra):
        from fedml_tpu.cross_silo import (
            FedMLCrossSiloClient,
            FedMLCrossSiloServer,
        )

        def mk(role, rank=0):
            over = dict(COMPRESSED_OVERRIDES, comm_round=rounds, role=role,
                        rank=rank, run_id=run_id, **extra)
            return fedml.init(Arguments(overrides=over),
                              should_init_logs=False)

        args_s = mk("server")
        ds, od = data_mod.load(args_s)
        bundle = model_mod.create(args_s, od)
        server = FedMLCrossSiloServer(args_s, None, ds, bundle)
        n = int(COMPRESSED_OVERRIDES["client_num_in_total"])
        clients = [FedMLCrossSiloClient(mk("client", r), None, ds, bundle)
                   for r in range(1, n + 1)]
        threads = [threading.Thread(target=c.run, daemon=True)
                   for c in clients]
        for t in threads:
            t.start()
        result = server.run()
        for t in threads:
            t.join(timeout=60)
        return result

    reg = telemetry.registry()
    short_r, long_r = 2, 10
    per_round, accs = {}, {}
    for tag, extra in (("uncompressed", dict(compression="", s2c_delta="off")),
                       ("compressed", dict(COMPRESSED_SCHEME))):
        b0 = reg.counter("comm.bytes_sent")
        run_world(f"bench-delta-{tag}-short-{os.getpid()}", short_r, extra)
        b_short = reg.counter("comm.bytes_sent") - b0
        b1 = reg.counter("comm.bytes_sent")
        res = run_world(f"bench-delta-{tag}-long-{os.getpid()}", long_r,
                        extra)
        b_long = reg.counter("comm.bytes_sent") - b1
        per_round[tag] = (b_long - b_short) / float(long_r - short_r)
        accs[tag] = float(res["test_acc"]) if res else 0.0

    counters = reg.snapshot()["counters"]
    reduction = (per_round["uncompressed"] / per_round["compressed"]
                 if per_round["compressed"] else 0.0)
    return {
        "compressed_bytes_per_round": round(per_round["compressed"], 1),
        "uncompressed_bytes_per_round": round(per_round["uncompressed"], 1),
        "compressed_reduction_x": round(reduction, 2),
        "compressed_acc": round(accs["compressed"], 4),
        "uncompressed_acc": round(accs["uncompressed"], 4),
        "compressed_scheme": "{compression}@{compression_ratio}".format(
            **COMPRESSED_SCHEME),
        "compressed_s2c_delta_frames": int(
            counters.get("comm.delta.s2c_delta_frames", 0)),
        "compressed_c2s_delta_decodes": int(
            counters.get("comm.delta.c2s_delta_decodes", 0)),
        "compressed_s2c_bytes_saved": int(
            counters.get("comm.delta.s2c_bytes_saved", 0)),
        "compressed_c2s_bytes_saved": int(
            counters.get("comm.delta.c2s_bytes_saved", 0)),
        "platform": jax.devices()[0].platform,
        "device_kind": jax.devices()[0].device_kind,
    }


def bench_fedavg_wire() -> dict:
    """Device-direct wire leg: serving-thread CPU s/MB to emit one S2C
    frame, full vs host-delta vs device-delta (see WIRE_DIM comment).

    Three parts, strict order: (1) the PARITY GATE — device frames must be
    byte-identical to the host codec's at the bench dim, or the leg raises
    and no number is reported; (2) the codec timing at WIRE_DIM; (3) an
    engagement proof — a short loopback swarm soak with ``--wire_path
    device`` whose report must show nonzero device encodes/decodes and
    zero host fallbacks.
    """
    _maybe_force_platform()
    import argparse

    import numpy as np

    import jax
    import jax.numpy as jnp

    from fedml_tpu.core.distributed.tensor_transport import encode_frames
    from fedml_tpu.delivery import DeltaCodec, WireCodec

    dim = int(os.environ.get("BENCH_WIRE_DIM", WIRE_DIM))
    reps = int(os.environ.get("BENCH_WIRE_REPS", "10"))
    rng = np.random.default_rng(0)
    base = rng.standard_normal(dim).astype(np.float32)
    new = base.copy()
    changed = rng.choice(dim, size=max(1, int(dim * WIRE_CHANGED_FRAC)),
                         replace=False)
    new[changed] += 0.01
    base_d, new_d = jnp.asarray(base), jnp.asarray(new)
    wire = WireCodec("device")

    # (1) parity gate — before any timing is believed
    h_arrays, h_meta = DeltaCodec.encode(base, new)
    d_arrays, d_meta = wire.encode(base_d, new_d)
    if h_meta != d_meta or (
            [np.asarray(a).tobytes() for a in h_arrays]
            != [np.asarray(a).tobytes() for a in d_arrays]):
        raise RuntimeError(
            f"device frames diverge from host codec at dim={dim} "
            f"(host {h_meta} vs device {d_meta})")

    # (2) timing: serving-thread CPU + wall, per path, after jit warmup
    def clock(fn):
        fn()  # warmup (compiles on the device path)
        w0, c0 = time.perf_counter(), time.thread_time()
        for _ in range(reps):
            fn()
        return ((time.perf_counter() - w0) / reps,
                (time.thread_time() - c0) / reps)

    mb = dim * 4 / 1e6
    paths = {
        "full": lambda: encode_frames([new]),
        "host_delta": lambda: DeltaCodec.encode(base, new),
        "device_delta": lambda: wire.encode(base_d, new_d),
    }
    timing = {}
    for tag, fn in paths.items():
        wall, cpu = clock(fn)
        timing[tag] = {"host_cpu_ms_per_mb": round(cpu / mb * 1e3, 4),
                       "wall_ms_per_mb": round(wall / mb * 1e3, 4)}

    # (3) engagement proof: short device-path soak, fallbacks must be zero
    from fedml_tpu.traffic.swarm import swarm_soak

    soak = swarm_soak(argparse.Namespace(
        clients=WIRE_SOAK["clients"], steps=WIRE_SOAK["steps"],
        buffer=0, staleness_alpha=0.5, max_staleness=0, flush_s=5.0,
        admit_rate=0.0, admit_burst=0, queue_limit=0,
        think_s=WIRE_SOAK["think_s"], dropout=0.0, seed=WIRE_SOAK["seed"],
        backend="loopback", procs=1, ranks_per_port=0, port=0,
        s2c_delta="auto", wire_path="device", timeout=120.0,
        run_id=f"bench-wire-{os.getpid()}",
    ))

    host_cpu = {t: v["host_cpu_ms_per_mb"] for t, v in timing.items()}
    reduction = (host_cpu["host_delta"] / host_cpu["device_delta"]
                 if host_cpu["device_delta"] else 0.0)
    return {
        "wire_dim": dim,
        "wire_frame_mb": round(mb, 1),
        "wire_scheme": h_meta["scheme"],
        "wire_parity": True,  # the gate above raised otherwise
        "wire_host_cpu_ms_per_mb": host_cpu,
        "wire_wall_ms_per_mb": {t: v["wall_ms_per_mb"]
                                for t, v in timing.items()},
        "wire_host_cpu_reduction_x": round(reduction, 2),
        "wire_soak_ok": bool(soak.get("ok")),
        "wire_soak_device_encodes": int(soak.get("wire_device_encodes") or 0),
        "wire_soak_device_decodes": int(soak.get("wire_device_decodes") or 0),
        "wire_soak_host_fallbacks": int(soak.get("wire_host_fallbacks") or 0),
        "wire_soak_s2c_delta_frames": int(soak.get("s2c_delta_frames") or 0),
        "platform": jax.devices()[0].platform,
        "device_kind": jax.devices()[0].device_kind,
    }


def bench_cheetah() -> dict:
    """Single-chip flagship-transformer pretrain throughput + MFU."""
    import gc

    _maybe_force_platform()
    import numpy as np

    import jax
    import jax.numpy as jnp

    from fedml_tpu.parallel.sharding import make_mesh
    from fedml_tpu.parallel.train_step import CheetahTrainer, make_optimizer
    from fedml_tpu.parallel.transformer import TransformerConfig

    platform = jax.devices()[0].platform
    if platform == "tpu":
        base, ladder = CHEETAH_BASE, CHEETAH_LADDER
        run = CHEETAH_RUN
    else:  # CPU smoke config so the bench degrades gracefully off-TPU
        base = dict(
            vocab_size=1024, d_model=256, n_heads=8,
            n_kv_heads=8, d_ff=704, max_seq_len=512, n_layers=4,
        )
        ladder = [dict(remat=False)]
        run = dict(batch=2, seq=256, steps=4, warmup=1)
    batch, seq = run["batch"], run["seq"]
    steps, warmup = run["steps"], run["warmup"]

    mesh = make_mesh()  # all local devices on the data axis
    rng = np.random.RandomState(0)

    state = trainer = cfg = None
    last_err = ""
    for rung in ladder:
        cfg = TransformerConfig(**{**base, **rung})
        trainer = CheetahTrainer(
            cfg, mesh,
            optimizer=make_optimizer(learning_rate=3e-4, warmup_steps=10,
                                     total_steps=steps + warmup,
                                     mu_dtype=jnp.bfloat16),
        )
        try:
            state = trainer.init_state(jax.random.PRNGKey(0))
            mask = jnp.ones((batch, seq), jnp.int32)
            tok = jnp.asarray(
                rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
            )
            state, metrics = trainer.train_step(state, tok, mask)
            _sync(metrics["loss"])
            break  # this rung compiles and fits
        except Exception as e:  # OOM at this rung: drop to more remat
            # keep only the repr — the traceback would pin the OOMed
            # trainer's buffers and poison the next rung's HBM headroom
            last_err = f"{type(e).__name__}: {e}"[:500]
            state = trainer = None
            gc.collect()
    if state is None:
        raise RuntimeError(f"no cheetah config fit on this chip: {last_err}")
    n_params = sum(int(p.size) for p in jax.tree.leaves(state.params))

    def batch_tokens():
        return jnp.asarray(
            rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
        )

    for _ in range(warmup):
        state, metrics = trainer.train_step(state, batch_tokens(), mask)
    _sync(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = trainer.train_step(state, batch_tokens(), mask)
    _sync(metrics["loss"])
    dt = time.perf_counter() - t0

    # tracked pass: two telemetry-instrumented steps AFTER the timed window
    # give the leg its data/step/loss_sync phase breakdown
    import tempfile
    import types

    from fedml_tpu.core import mlops
    from fedml_tpu.core.mlops import telemetry

    targs = types.SimpleNamespace(
        enable_tracking=True, run_id=f"bench_cheetah_{os.getpid()}", rank=0,
        tracking_dir=(os.environ.get("BENCH_TRACKING_DIR")
                      or tempfile.mkdtemp(prefix="fedml_bench_track_")),
    )
    mlops.init(targs)
    for i in range(2):
        rec = telemetry.begin_round(i)
        with telemetry.phase("data"):
            tok = batch_tokens()
        with telemetry.phase("step"):
            state, metrics = trainer.train_step(state, tok, mask)
        with telemetry.phase("loss_sync"):
            _sync(metrics["loss"])
        if rec is not None:
            rec.lazy["examples"] = tok.size
        telemetry.end_round(rec)
    phases, _ = mlops.phase_totals(mlops.read_events())
    mlops.close()

    tokens = steps * batch * seq
    tps = tokens / dt
    # model FLOPs per token (fwd+bwd): 6N matmul + 12·L·layers·d_model attn
    flops_per_token = 6.0 * n_params + 12.0 * seq * cfg.n_layers * cfg.d_model
    achieved = tps * flops_per_token
    kind = jax.devices()[0].device_kind
    peak = TPU_PEAK_FLOPS.get(kind)
    n_chips = jax.device_count()
    out = {
        "cheetah_tokens_per_sec_per_chip": round(tps / n_chips, 1),
        "cheetah_params_m": round(n_params / 1e6, 1),
        "cheetah_seq_len": seq,
        "cheetah_device_kind": kind,
        "cheetah_remat": cfg.remat_policy if cfg.remat else "none",
        "cheetah_phases": {k: round(v, 4) for k, v in phases.items()},
        "platform": platform,
    }
    if peak:
        out["cheetah_mfu"] = round(achieved / (peak * n_chips), 4)
    return out


# ---------------------------------------------------------------------------
# Parent orchestrator (never imports jax)
# ---------------------------------------------------------------------------


def _digest(cfg, src_paths) -> str:
    """Cache key for a leg: its config + the source files that produce it."""
    h = hashlib.md5(json.dumps(cfg, sort_keys=True).encode())
    for rel in src_paths:
        p = os.path.join(HERE, rel)
        try:
            with open(p, "rb") as f:
                h.update(f.read())
        except OSError:
            h.update(b"missing:" + rel.encode())
    return h.hexdigest()


def _load_partial() -> dict:
    try:
        with open(PARTIAL_PATH) as f:
            d = json.load(f)
        if isinstance(d.get("legs"), dict):
            return d
    except (OSError, ValueError):
        pass
    return {"legs": {}}


def _write_partial(name: str, row: dict) -> None:
    """Checkpoint one completed leg. Read-modify-write per leg (not a dump of
    this run's start-of-run snapshot) so two overlapping bench runs — the
    insurance scenario — merge rather than clobber each other. The file is
    deliberately TRACKED in git: a TPU-measured row committed mid-round lets
    the driver's end-of-round run survive a wedged tunnel."""
    lock_path = PARTIAL_PATH + ".lock"
    with open(lock_path, "w") as lock:
        try:
            import fcntl

            fcntl.flock(lock, fcntl.LOCK_EX)  # overlapping runs serialize
        except ImportError:  # non-POSIX: best-effort read-modify-write
            pass
        cache = _load_partial()
        cache["legs"][name] = row
        cache["updated"] = time.time()
        tmp = PARTIAL_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(cache, f, indent=1)
        os.replace(tmp, PARTIAL_PATH)


def _translate_mfu(prefix: str, parsed: dict):
    """mfu_sweep.py --one output → prefixed bench keys (+ platform)."""
    if "skipped" in parsed:  # CPU-only host: the child declined the TPU shape
        return {}, "cpu"
    res = {
        f"{prefix}_mfu": parsed["mfu"],
        f"{prefix}_tokens_per_sec_per_chip": parsed["tok_s"],
        f"{prefix}_device_kind": parsed.get("device_kind"),
    }
    if "params_active_m" in parsed:
        res[f"{prefix}_params_active_m"] = parsed["params_active_m"]
        res[f"{prefix}_params_total_m"] = parsed["params_m"]
    return res, "tpu"


def _translate_fedavg(parsed: dict):
    platform = parsed.get("platform")
    extras = {
        k: parsed[k]
        for k in ("fedavg_compile_s", "fedavg_round_fused",
                  "fedavg_superround_k", "fedavg_phases",
                  "fedavg_phase_rounds", "fedavg_tracked_wall_s",
                  "fedavg_compile_cache_hits", "fedavg_compile_cache_misses",
                  "fedavg_resume_overhead_s")
        if k in parsed
    }
    if platform != "tpu":
        # never let the smoke config masquerade as the resnet56 metric:
        # the headline "value" stays null off-TPU
        return {"fedavg_cpu_smoke_rounds_per_sec": parsed["rounds_per_sec"],
                "fedavg_note": "cpu smoke (lr/mnist) — not reference-comparable",
                "fedavg_device_kind": parsed.get("device_kind"),
                **extras}, platform
    return {"rounds_per_sec": parsed["rounds_per_sec"],
            "fedavg_device_kind": parsed.get("device_kind"),
            **extras}, platform


def _translate_cheetah(parsed: dict):
    platform = parsed.pop("platform", None)
    return parsed, platform


def _translate_million(parsed: dict):
    platform = parsed.pop("platform", None)
    out = {"million_device_kind": parsed.pop("device_kind", None), **parsed}
    return out, platform


def _translate_compressed(parsed: dict):
    platform = parsed.pop("platform", None)
    out = {"compressed_device_kind": parsed.pop("device_kind", None),
           **parsed}
    return out, platform


def _translate_wire(parsed: dict):
    platform = parsed.pop("platform", None)
    out = {"wire_device_kind": parsed.pop("device_kind", None), **parsed}
    return out, platform


def leg_specs() -> list:
    """(name, argv, digest, translate) per leg, priority order: the headline
    FedAvg metric first, then the flagship, then the secondary shapes."""
    mfu = os.path.join(HERE, "tools", "mfu_sweep.py")
    me = os.path.join(HERE, "bench.py")
    py = sys.executable
    million_n = int(os.environ.get("BENCH_REGISTRY_N", MILLION_REGISTRY_N))
    million_k = int(os.environ.get("BENCH_COHORT_K", MILLION_COHORT_K))
    return [
        ("fedavg", [py, me, "--leg", "fedavg"],
         _digest(FEDAVG_OVERRIDES, _FEDAVG_SOURCES), _translate_fedavg),
        ("fedavg_million_client", [py, me, "--leg", "million"],
         _digest({"cfg": MILLION_OVERRIDES, "n": million_n, "k": million_k},
                 _MILLION_SOURCES), _translate_million),
        ("fedavg_compressed_round", [py, me, "--leg", "compressed"],
         _digest({"cfg": COMPRESSED_OVERRIDES, "scheme": COMPRESSED_SCHEME},
                 _COMPRESSED_SOURCES), _translate_compressed),
        ("fedavg_wire", [py, me, "--leg", "wire"],
         _digest({"dim": WIRE_DIM, "frac": WIRE_CHANGED_FRAC,
                  "soak": WIRE_SOAK}, _WIRE_SOURCES), _translate_wire),
        ("cheetah", [py, me, "--leg", "cheetah"],
         _digest({"base": CHEETAH_BASE, "ladder": CHEETAH_LADDER,
                  "run": CHEETAH_RUN}, _CHEETAH_SOURCES), _translate_cheetah),
        ("cheetah_hd512", [py, mfu, "--one", json.dumps(HD512_CFG)],
         _digest(HD512_CFG, _CHEETAH_SOURCES),
         lambda p: _translate_mfu("cheetah_hd512", p)),
        ("cheetah_remat", [py, mfu, "--one", json.dumps(REMAT_CFG)],
         _digest(REMAT_CFG, _CHEETAH_SOURCES),
         lambda p: _translate_mfu("cheetah_remat", p)),
        ("cheetah_moe", [py, mfu, "--one", json.dumps(MOE_CFG)],
         _digest(MOE_CFG, _CHEETAH_SOURCES),
         lambda p: _translate_mfu("cheetah_moe", p)),
    ]


def build_line(results: dict, ref: float | None, meta: dict) -> dict:
    """Assemble the cumulative JSON line from completed leg results."""
    fed = results.get("fedavg", {})
    value = fed.get("rounds_per_sec")
    comparable = value is not None and "fedavg_note" not in fed
    line = {
        "metric": "fedavg_rounds_per_sec_100clients_cifar10_resnet56",
        "value": round(value, 4) if value is not None else None,
        "unit": "rounds/s",
        # TPU vs the reference's torch CPU (its only substrate here) —
        # conflates hardware with architecture, hence the companion below
        "vs_baseline": round(value / ref, 2) if (comparable and ref) else None,
        "ref_rounds_per_sec_measured": ref,
        # ours-on-CPU / reference-on-CPU: the architectural win alone
        **_same_substrate(),
    }
    for name, res in results.items():
        for k, v in res.items():
            if k != "rounds_per_sec":
                line[k] = v
    line.update(meta)
    return line


def _probe_device_kind(timeout: float = 90.0):
    """Ask a SUBPROCESS for the device kind (a wedged tunnel hangs the
    probe, not the bench). Returns ``(kind, reason)``:

    - ``(str, "ok")`` — chip identified;
    - ``(None, "timeout")`` — probe exceeded its budget: could be a DOWN
      tunnel or merely a SLOW-but-healthy host, so callers must NOT treat
      this as proof of unreachability;
    - ``(None, "error")`` — backend init failed fast (e.g. UNAVAILABLE):
      the one case where legs are certain to fail too.

    None kinds ACCEPT cached rows (the insurance case) rather than
    discarding them."""
    # honor BENCH_PLATFORM in the probe snippet: a bare `import jax` dials
    # the pinned axon backend (see _maybe_force_platform), so on a
    # BENCH_PLATFORM=cpu host the probe would burn up to `timeout` seconds
    # on a tunnel the legs never touch — and its "error"/"timeout" verdict
    # would needlessly shrink leg timeouts for legs that run fine on CPU
    plat = os.environ.get("BENCH_PLATFORM", "")
    snippet = "import jax; "
    if plat:
        snippet += f"jax.config.update('jax_platforms', {plat!r}); "
    snippet += "print(jax.devices()[0].device_kind)"
    try:
        p = subprocess.run(
            [sys.executable, "-c", snippet],
            capture_output=True, text=True, timeout=timeout,
        )
        if p.returncode == 0 and p.stdout.strip():
            return p.stdout.strip().splitlines()[-1], "ok"
        return None, "error"
    except subprocess.TimeoutExpired:
        return None, "timeout"
    except Exception:
        return None, "error"


def _usable(cached, digest: str, ttl_s: float) -> bool:
    return bool(
        cached and cached.get("digest") == digest
        and cached.get("platform") == "tpu"
        and time.time() - cached.get("t", 0) < ttl_s
    )


def run_legs(budget_s: float, ttl_s: float, min_leg_s: float = 240.0,
             leg_timeout_s: float = 900.0, runner=None,
             device_prober=None) -> dict:
    """Run all legs under a global deadline, emitting the cumulative line
    after every completed leg. ``runner``/``device_prober`` are injectable
    for tests."""
    t_start = time.monotonic()
    cache = _load_partial()
    ref = _ref_rounds_per_sec()
    results: dict = {}

    # one up-front device probe (in a SUBPROCESS — a wedged tunnel hangs the
    # probe, not the bench). Purpose is twofold: (a) a cache row measured on
    # a DIFFERENT TPU generation must not be served as this round's number —
    # mismatched rows are dropped and re-run; (b) when the tunnel is
    # UNREACHABLE, every leg would hang to its full timeout at backend init,
    # so leg timeouts shrink to fail fast and the line carries explicit
    # errors within minutes instead of rc=124.
    specs = leg_specs()
    # BENCH_LEGS=fedavg,cheetah runs a subset (smoke checks / re-measuring
    # one leg without paying for the rest); unknown names are ignored
    only = os.environ.get("BENCH_LEGS", "").strip()
    if only:
        wanted = {n.strip() for n in only.split(",") if n.strip()}
        specs = [s for s in specs if s[0] in wanted]
    probe = (device_prober or _probe_device_kind)()
    # tolerate simple probers that return a bare kind (tests inject these)
    kind, reason = probe if isinstance(probe, tuple) else (probe, "ok")
    if kind is None and reason == "error":
        # backend init fails FAST and deterministically (tunnel down): legs
        # would each hang their full timeout at init, so fail fast instead.
        # A probe TIMEOUT is NOT proof of unreachability (a loaded host can
        # blow the 90s budget and still serve legs fine) — keep timeouts.
        leg_timeout_s = min(leg_timeout_s, 240.0)
    for n, _, d, _ in specs:
        row = cache["legs"].get(n)
        if (_usable(row, d, ttl_s) and kind and row.get("device_kind")
                and row["device_kind"] != kind):
            del cache["legs"][n]

    def emit():
        elapsed = round(time.monotonic() - t_start, 1)
        line = build_line(results, ref, {"bench_elapsed_s": elapsed,
                                         "bench_budget_s": budget_s,
                                         "bench_device_probe":
                                         kind or {"error": "unreachable",
                                                  "timeout": "probe-timeout"}
                                         .get(reason, "unknown")})
        print(json.dumps(line), flush=True)
        return line

    # a parseable tail exists from second zero: even a driver timeout before
    # the FIRST leg resolves leaves this line, not an empty capture (r4
    # recorded rc=124 with tail="")
    emit()

    def default_runner(argv, timeout):
        env = dict(os.environ)
        env["PYTHONPATH"] = HERE + os.pathsep + env.get("PYTHONPATH", "")
        p = subprocess.run(argv, capture_output=True, text=True,
                           timeout=timeout, env=env)
        out = (p.stdout.strip().splitlines() or ["<no output>"])[-1]
        if p.returncode != 0:
            err = (p.stderr.strip().splitlines() or [""])[-1]
            raise RuntimeError(f"rc={p.returncode} {out[:120]} {err[:200]}")
        return json.loads(out)

    runner = runner or default_runner
    line = {}
    for name, argv, digest, translate in specs:
        cached = cache["legs"].get(name)
        if _usable(cached, digest, ttl_s):
            results[name] = {**cached["result"], f"{name}_cached": True}
            line = emit()
            continue
        remaining = budget_s - (time.monotonic() - t_start)
        if remaining < min_leg_s:
            results[name] = {f"{name}_skipped": "budget"}
            line = emit()
            continue
        t0 = time.time()
        try:
            parsed = runner(argv, min(leg_timeout_s, remaining))
            res, platform = translate(parsed)
        except subprocess.TimeoutExpired:
            res, platform = {f"{name}_error": "leg timeout"}, None
        except Exception as e:
            res, platform = (
                {f"{name}_error": f"{type(e).__name__}: {e}"[:300]}, None)
        results[name] = res
        if platform == "tpu":  # only real-config TPU numbers are cacheable
            _write_partial(name, {
                "digest": digest, "t": time.time(), "platform": platform,
                "dur_s": round(time.time() - t0, 1), "result": res,
                "device_kind": next(
                    (v for k2, v in res.items()
                     if k2.endswith("device_kind") and v), None),
            })
        line = emit()
    return line


def main() -> None:
    if len(sys.argv) > 2 and sys.argv[1] == "--leg":
        fn = {"fedavg": bench_fedavg, "cheetah": bench_cheetah,
              "million": bench_million_client,
              "compressed": bench_compressed_round,
              "wire": bench_fedavg_wire}[sys.argv[2]]
        print(json.dumps(fn()), flush=True)
        return
    budget = float(os.environ.get("BENCH_BUDGET_S", "2400"))
    ttl = float(os.environ.get("BENCH_CACHE_TTL_S", str(7 * 86400)))
    min_leg = float(os.environ.get("BENCH_MIN_LEG_S", "240"))
    leg_timeout = float(os.environ.get("BENCH_LEG_TIMEOUT_S", "900"))
    run_legs(budget, ttl, min_leg_s=min_leg, leg_timeout_s=leg_timeout)


if __name__ == "__main__":
    main()
