"""Headline benchmark — run on real TPU by the driver each round.

Metric (BASELINE.json north star): Parrot FedAvg rounds/sec with 100 simulated
clients on CIFAR-10-shaped data, ResNet-20, 10 clients/round, 1 local epoch.
The reference publishes no throughput baseline (``published = {}``), so
``vs_baseline`` is measured against a fixed reference point: the reference's
single-process torch loop timed at ~REF_ROUNDS_PER_SEC on this class of config
(its per-round cost is dominated by K sequential client loops; ours is one
fused vmap program). Until a measured torch/GPU number exists, REF is an
estimated 0.2 rounds/s (5 s/round for 10 ResNet-20 clients × 1 epoch × 500
samples, typical of the reference's sp backend on a single accelerator).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import time

REF_ROUNDS_PER_SEC = 0.2  # estimated reference sp-backend throughput


def main() -> None:
    import fedml_tpu as fedml
    from fedml_tpu import data as data_mod
    from fedml_tpu import models as model_mod
    from fedml_tpu.arguments import Arguments
    from fedml_tpu.simulation.sp_api import FedAvgAPI

    args = Arguments(overrides=dict(
        dataset="cifar10", model="resnet20", client_num_in_total=100,
        client_num_per_round=10, comm_round=12, epochs=1, batch_size=32,
        learning_rate=0.1, frequency_of_the_test=1000,
    ))
    args.train_dtype = "bf16"  # MXU-native compute, fp32 master weights
    args = fedml.init(args, should_init_logs=False)
    ds, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    api = FedAvgAPI(args, fedml.get_device(args), ds, bundle)

    # warmup (compile) — 2 rounds
    for r in range(2):
        args.round_idx = r
        api._train_round(r)

    n_rounds = 10
    t0 = time.perf_counter()
    for r in range(2, 2 + n_rounds):
        args.round_idx = r
        api._train_round(r)
    # block on the result
    import jax

    jax.block_until_ready(api.global_params)
    dt = time.perf_counter() - t0

    value = n_rounds / dt
    print(json.dumps({
        "metric": "fedavg_rounds_per_sec_100clients_cifar10_resnet20",
        "value": round(value, 4),
        "unit": "rounds/s",
        "vs_baseline": round(value / REF_ROUNDS_PER_SEC, 2),
    }))


if __name__ == "__main__":
    main()
