"""Cross-silo client (reference: quick_start/octopus/client/).

    python client.py --cf fedml_config.yaml --rank 1 --role client
    python client.py --cf fedml_config.yaml --rank 2 --role client

A silo with several local chips adds intra-silo data parallelism with
`--silo_device_indices 0 1 ...` (one jit over a local mesh, per-step
gradient psum — the torch-DDP analog on ICI).
"""

import fedml_tpu as fedml

if __name__ == "__main__":
    print(fedml.run_cross_silo_client())
