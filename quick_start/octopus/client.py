"""Cross-silo client (reference: quick_start/octopus/client/).

    python client.py --cf fedml_config.yaml --rank 1 --role client
    python client.py --cf fedml_config.yaml --rank 2 --role client

A silo with several local chips adds intra-silo data parallelism with
`--silo_device_indices 0 1 ...` (one jit over a local mesh, per-step
gradient psum — the torch-DDP analog on ICI).
"""

# run-from-checkout shim: make the repo importable without `pip install -e .`
import os as _os, sys as _sys
_sys.path.insert(0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), "..", "..")))

import fedml_tpu as fedml

if __name__ == "__main__":
    print(fedml.run_cross_silo_client())
