"""Cross-silo server (reference: quick_start/octopus/server/).

    python server.py --cf fedml_config.yaml --rank 0 --role server
"""

# run-from-checkout shim: make the repo importable without `pip install -e .`
import os as _os, sys as _sys
_sys.path.insert(0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), "..", "..")))

import fedml_tpu as fedml

if __name__ == "__main__":
    print(fedml.run_cross_silo_server())
