"""Cross-silo server (reference: quick_start/octopus/server/).

    python server.py --cf fedml_config.yaml --rank 0 --role server
"""

import fedml_tpu as fedml

if __name__ == "__main__":
    print(fedml.run_cross_silo_server())
