"""Same federation, every local chip: the cohort shards over a `clients`
mesh axis (replaces the reference's MPI/NCCL simulators).

    python mesh_example.py --cf fedml_config.yaml
"""

import fedml_tpu as fedml

if __name__ == "__main__":
    print(fedml.run_simulation(backend="mesh"))
