"""One-line simulated FL (reference:
quick_start/parrot/torch_fedavg_mnist_lr_one_line_example.py).

    python one_line_example.py --cf fedml_config.yaml
"""

# run-from-checkout shim: make the repo importable without `pip install -e .`
import os as _os, sys as _sys
_sys.path.insert(0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), "..", "..")))

import fedml_tpu as fedml

if __name__ == "__main__":
    print(fedml.run_simulation())
