"""One-line simulated FL (reference:
quick_start/parrot/torch_fedavg_mnist_lr_one_line_example.py).

    python one_line_example.py --cf fedml_config.yaml
"""

import fedml_tpu as fedml

if __name__ == "__main__":
    print(fedml.run_simulation())
