"""Step-by-step API (reference:
quick_start/parrot/torch_fedavg_mnist_lr_step_by_step_example.py):
init -> device -> data -> model -> runner.
"""

# run-from-checkout shim: make the repo importable without `pip install -e .`
import os as _os, sys as _sys
_sys.path.insert(0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), "..", "..")))

import fedml_tpu as fedml
from fedml_tpu import data as fedml_data
from fedml_tpu import models as fedml_models
from fedml_tpu.runner import FedMLRunner

if __name__ == "__main__":
    args = fedml.init()
    device = fedml.get_device(args)
    dataset, output_dim = fedml_data.load(args)
    model = fedml_models.create(args, output_dim)
    runner = FedMLRunner(args, device, dataset, model)
    print(runner.run())
