"""Custom data + model (reference:
quick_start/parrot/torch_fedavg_mnist_lr_custum_data_and_model_example.py):
bring your own flax module; everything else is unchanged.
"""

# run-from-checkout shim: make the repo importable without `pip install -e .`
import os as _os, sys as _sys
_sys.path.insert(0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), "..", "..")))

import fedml_tpu as fedml
import jax.numpy as jnp
from fedml_tpu import data as fedml_data
from fedml_tpu.models import ModelBundle
from fedml_tpu.runner import FedMLRunner
from flax import linen as nn


class TwoLayerNet(nn.Module):
    num_classes: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = x.reshape((x.shape[0], -1))
        h = nn.relu(nn.Dense(128)(h))
        return nn.Dense(self.num_classes)(h)


if __name__ == "__main__":
    args = fedml.init()
    device = fedml.get_device(args)
    dataset, output_dim = fedml_data.load(args)
    model = ModelBundle(
        module=TwoLayerNet(output_dim),
        name="two_layer_net",
        input_shape=tuple(dataset.train_x.shape[2:]),
        input_dtype=jnp.float32,
        task=dataset.task,
    )
    runner = FedMLRunner(args, device, dataset, model)
    print(runner.run())
