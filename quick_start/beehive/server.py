"""Beehive quick start: cross-device FL server in one file.

reference: ``python/quick_start/beehive/torch_server.py`` — launch the MNN
artifact server that mobile clients federate against (``fedml.run_mnn_server``).

TPU re-grounding: the artifact plane is ``.npz`` tensor files
(``cross_device/server.py``) — the open contract a mobile client speaks:
download ``global_model_file_path``, train locally, drop ``client_*.npz``
(+ ``.samples`` weight sidecar) into ``device_upload_dir``. This demo plays
both sides so it runs anywhere: background threads act as two "devices"
that poll the published global, take a simulated local step, and upload.

Run: ``python server.py``.
"""

# run-from-checkout shim: make the repo importable without `pip install -e .`
import os as _os, sys as _sys
_sys.path.insert(0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), "..", "..")))

import os
import shutil
import sys
import threading
import time

import numpy as np

import fedml_tpu as fedml
from fedml_tpu import data as fedml_data
from fedml_tpu import models as fedml_models
from fedml_tpu.arguments import Arguments
from fedml_tpu.cross_device.server import (
    ServerMNN,
    read_artifact_as_tensor_dict,
    write_tensor_dict_to_artifact,
)

HERE = os.path.dirname(os.path.abspath(__file__))
WORK = os.path.join(HERE, ".beehive_demo")
GLOBAL = os.path.join(WORK, "global_model.npz")
UPLOADS = os.path.join(WORK, "uploads")      # devices drop files here
STAGING = os.path.join(WORK, "staging")      # server ingests from here


def local_sgd(tensors, x, y, lr=0.1, epochs=5):
    """A phone's local training, in plain numpy: softmax regression SGD on
    the device's own shard — what the MNN engine does on-device."""
    kernel_key = next(k for k, v in tensors.items()
                      if v.ndim == 2 and "kernel" in k.lower())
    bias_key = next(k for k, v in tensors.items()
                    if v.ndim == 1 and "bias" in k.lower())
    w, b = tensors[kernel_key].copy(), tensors[bias_key].copy()
    xf = x.reshape(x.shape[0], -1).astype(np.float32)
    onehot = np.eye(w.shape[1], dtype=np.float32)[y]
    for _ in range(epochs):
        logits = xf @ w + b
        logits -= logits.max(1, keepdims=True)
        p = np.exp(logits)
        p /= p.sum(1, keepdims=True)
        g = (p - onehot) / len(y)
        w -= lr * (xf.T @ g)
        b -= lr * g.sum(0)
    out = dict(tensors)
    out[kernel_key], out[bias_key] = w, b
    return out


def fake_device(device_id: str, rounds: int, x, y) -> None:
    """Stands in for a phone: poll the global artifact, train, upload."""
    seen = -1.0
    for _ in range(rounds):
        while True:  # wait for a (re)published global model
            try:
                mtime = os.path.getmtime(GLOBAL)
            except OSError:
                mtime = -1.0
            if mtime > seen:
                seen = mtime
                break
            time.sleep(0.1)
        # publish is atomic (temp-file + os.replace), so a visible mtime
        # change means a complete archive — no grace sleep needed
        tensors = read_artifact_as_tensor_dict(GLOBAL)
        updated = local_sgd(tensors, x, y)
        path = os.path.join(UPLOADS, f"client_{device_id}.npz")
        write_tensor_dict_to_artifact(updated, path)
        with open(path[:-4] + ".samples", "w") as f:
            f.write(str(len(y)))


def main() -> None:
    shutil.rmtree(WORK, ignore_errors=True)
    os.makedirs(UPLOADS, exist_ok=True)
    os.makedirs(STAGING, exist_ok=True)
    args = fedml.init(Arguments(overrides=dict(
        training_type="cross_device", dataset="mnist", model="lr",
        client_num_in_total=2, client_num_per_round=2, comm_round=3,
        global_model_file_path=GLOBAL, device_upload_dir=STAGING,
    )), should_init_logs=False)
    dataset, output_dim = fedml_data.load(args)
    model = fedml_models.create(args, output_dim)

    server = ServerMNN(args, fedml.get_device(args), dataset, model)
    server.publish_global_model()
    for idx, device_id in enumerate(("device-a", "device-b")):
        x, y, n = dataset.client_shard(idx)
        threading.Thread(
            target=fake_device,
            args=(device_id, args.comm_round,
                  np.asarray(x)[: int(n)], np.asarray(y)[: int(n)]),
            daemon=True,
        ).start()

    n_devices = 2
    for _ in range(args.comm_round):
        # wait for BOTH devices' sidecars (written last), then move the
        # round's uploads into staging — devices racing ahead into the next
        # round keep writing to UPLOADS and are never clobbered
        while len([f for f in os.listdir(UPLOADS)
                   if f.endswith(".samples")]) < n_devices:
            time.sleep(0.1)
        for f in os.listdir(UPLOADS):
            os.replace(os.path.join(UPLOADS, f), os.path.join(STAGING, f))
        server.run_one_round()  # ingests staging, republishes the global
        for f in os.listdir(STAGING):
            os.remove(os.path.join(STAGING, f))

    print(f"beehive quick start: {server.round_idx} cross-device rounds "
          f"complete, final acc="
          f"{(server.final_metrics or {}).get('test_acc', float('nan')):.3f}")


if __name__ == "__main__":
    sys.exit(main())
