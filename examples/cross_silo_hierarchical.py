"""Hierarchical cross-silo: a 2-chip silo (per-step gradient psum over a
local mesh) + a silo with a DCN slave (round-level averaging)."""

# run-from-checkout shim: make the repo importable without `pip install -e .`
import os as _os, sys as _sys
_sys.path.insert(0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), "..")))

import threading
import time

import fedml_tpu as fedml
from fedml_tpu import data as data_mod, models as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.cross_silo import FedMLCrossSiloClient, FedMLCrossSiloServer


def mk(**kw):
    base = dict(training_type="cross_silo", dataset="synthetic", model="lr",
                client_num_in_total=2, client_num_per_round=2, comm_round=4,
                epochs=2, batch_size=8, learning_rate=0.2,
                backend="LOOPBACK", run_id="hier-demo")
    base.update(kw)
    return fedml.init(Arguments(overrides=base), should_init_logs=False)


args_s = mk(role="server")
ds, od = data_mod.load(args_s)
bundle = model_mod.create(args_s, od)
server = FedMLCrossSiloServer(args_s, None, ds, bundle)
import jax

silo1 = dict(silo_device_indices=[0, 1]) if len(jax.devices()) >= 2 else {}
clients = [
    FedMLCrossSiloClient(mk(role="client", rank=1, **silo1), None, ds, bundle),
    FedMLCrossSiloClient(mk(role="client", rank=2, silo_proc_num=2), None, ds, bundle),
]
threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
for t in threads:
    t.start()
time.sleep(0.1)
print(server.run())
