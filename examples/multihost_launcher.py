"""Multi-host launch: one logical mesh spanning OS processes.

The analog of the reference's ``mpirun -np N`` MPI plane
(``simulation/mpi/base_framework/``): ``spawn`` starts N coordinated
processes, each joins via ``jax.distributed.initialize`` through
``multihost.initialize()``, and afterwards the SAME mesh programs used
everywhere else run across all of them — XLA routes collectives between
processes, no send/recv code anywhere.

Run: ``python multihost_launcher.py`` (launcher) — spawns 2 workers × 2
virtual CPU devices and sums a globally-sharded array across the processes.
On a real pod, skip spawn: run one process per host and call
``multihost.initialize()`` with no args.
"""

# run-from-checkout shim: make the repo importable without `pip install -e .`
import os as _os, sys as _sys
_sys.path.insert(0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), "..")))

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def worker() -> None:
    from fedml_tpu.parallel.multihost import initialize

    initialize()  # reads the FEDML_TPU_* env contract set by spawn()

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from fedml_tpu.parallel.sharding import make_mesh

    mesh = make_mesh({"data": jax.device_count()})
    x = jax.jit(
        lambda: jnp.arange(float(jax.device_count())),
        out_shardings=NamedSharding(mesh, P("data")),
    )()
    total = float(jax.jit(jnp.sum)(x))  # cross-process collective
    print(f"rank {jax.process_index()}/{jax.process_count()}: "
          f"{jax.local_device_count()} local of {jax.device_count()} global "
          f"devices, global sum = {total}")


def launcher() -> None:
    from fedml_tpu.parallel.multihost import spawn

    results = spawn(
        [os.path.abspath(__file__), "--worker"],
        n_processes=2, local_device_count=2,
        env={"JAX_PLATFORMS": "cpu",
             "PYTHONPATH": ":".join(
                 p for p in (REPO, os.environ.get("PYTHONPATH", "")) if p)},
    )
    for r in results:
        sys.stdout.write(r.stdout)
    print("multihost launch ok")


if __name__ == "__main__":
    if "--worker" in sys.argv:
        worker()
    else:
        launcher()
