"""EF-TopK compressed update deltas + payload-by-reference transport."""

# run-from-checkout shim: make the repo importable without `pip install -e .`
import os as _os, sys as _sys
_sys.path.insert(0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), "..")))

import tempfile
import threading
import time

import fedml_tpu as fedml
from fedml_tpu import data as data_mod, models as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.cross_silo import FedMLCrossSiloClient, FedMLCrossSiloServer

store = tempfile.mkdtemp(prefix="fedml-payloads-")


def mk(**kw):
    base = dict(training_type="cross_silo", dataset="synthetic", model="lr",
                client_num_in_total=2, client_num_per_round=2, comm_round=4,
                epochs=2, batch_size=16, learning_rate=0.2,
                backend="LOOPBACK", run_id="comp-demo",
                compression="eftopk", compression_ratio=0.1,
                payload_store_dir=store, payload_inline_limit_bytes=256)
    base.update(kw)
    return fedml.init(Arguments(overrides=base), should_init_logs=False)


args_s = mk(role="server")
ds, od = data_mod.load(args_s)
bundle = model_mod.create(args_s, od)
server = FedMLCrossSiloServer(args_s, None, ds, bundle)
clients = [FedMLCrossSiloClient(mk(role="client", rank=r), None, ds, bundle)
           for r in (1, 2)]
threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
for t in threads:
    t.start()
time.sleep(0.1)
print(server.run())
