"""Application layer in one file: FedGraphNN / FedNLP / FedCV / healthcare.

reference: ``python/app/`` — per-domain application dirs (fedgraphnn,
fednlp, fedcv, healthcare; 456 files). Here every app task is the same
five-line program with a different (dataset, model) pair, because each
domain reduced to a (spec, model, loss) triple on the one engine.

Run: ``python app_tasks.py`` (~a minute per task on one chip).
"""

# run-from-checkout shim: make the repo importable without `pip install -e .`
import os as _os, sys as _sys
_sys.path.insert(0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), "..")))

import fedml_tpu as fedml
from fedml_tpu import data as data_mod
from fedml_tpu import models as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.runner import FedMLRunner

TASKS = [
    # (banner, dataset, model, extra args)
    ("FedGraphNN molecule graph clf", "moleculenet_clf", "gcn", {}),
    ("FedGraphNN molecule graph reg", "moleculenet_reg", "gcn",
     dict(learning_rate=0.02)),
    ("FedGraphNN ego node clf", "ego_node_clf", "sage", {}),
    ("FedGraphNN ego link pred", "ego_link_pred", "gcn", {}),
    # LSTMs under plain SGD need a hot lr and a few more rounds
    ("FedNLP sequence tagging", "fednlp_seq_tagging", "bilstm_tagger",
     dict(learning_rate=1.0, comm_round=12, epochs=3)),
    ("FedNLP span extraction", "fednlp_span_extraction", "span_extractor",
     dict(learning_rate=1.0, comm_round=12, epochs=3)),
    # reversal is a copy task: attention learns it, a small LSTM cannot
    ("FedNLP seq2seq (prefix-LM)", "fednlp_seq2seq", "transformer",
     dict(learning_rate=0.3, comm_round=12, epochs=3)),
    ("FedCV detection", "coco128_det", "centernet",
     dict(batch_size=8, learning_rate=0.05)),
    ("Healthcare heart disease", "fed_heart_disease", "lr", {}),
    ("Healthcare TCGA-BRCA survival", "fed_tcga_brca", "lr",
     dict(learning_rate=0.05)),
]


def run_task(banner, dataset, model, extra):
    overrides = dict(
        dataset=dataset, model=model, client_num_in_total=8,
        client_num_per_round=8, comm_round=8, epochs=2, batch_size=16,
        learning_rate=0.1, frequency_of_the_test=20, backend="sp",
    )
    overrides.update(extra)
    args = fedml.init(Arguments(overrides=overrides), should_init_logs=False)
    ds, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    res = FedMLRunner(args, fedml.get_device(args), ds, bundle).run()
    acc = res.get("test_acc")
    print(f"{banner:34s} loss={res['test_loss']:.3f}"
          + (f" acc={acc:.3f}" if acc == acc else ""))


if __name__ == "__main__":
    for task in TASKS:
        run_task(*task)
