"""Cheetah: sharded LLM pretraining over a dp/fsdp/tp mesh. On a 1-chip
host the mesh collapses to single-device; same program either way."""

# run-from-checkout shim: make the repo importable without `pip install -e .`
import os as _os, sys as _sys
_sys.path.insert(0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), "..")))

import fedml_tpu as fedml
from fedml_tpu.arguments import Arguments
from fedml_tpu.runner import FedMLRunner

args = fedml.init(Arguments(overrides=dict(
    training_type="distributed", dataset="shakespeare", model="transformer",
    model_size="tiny", vocab_size=90, total_steps=30, batch_size=8,
    seq_len=64, client_num_in_total=8, client_num_per_round=8,
    learning_rate=3e-3, warmup_steps=5,
)), should_init_logs=False)
from fedml_tpu import data as data_mod

ds, _ = data_mod.load(args)
print(FedMLRunner(args, fedml.get_device(args), ds, None).run())
