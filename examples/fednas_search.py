"""Federated DARTS search: weights + alphas averaged every round."""

import fedml_tpu as fedml
from fedml_tpu import data as data_mod, models as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.runner import FedMLRunner

args = fedml.init(Arguments(overrides=dict(
    dataset="synthetic", model="darts", federated_optimizer="FedNAS",
    client_num_in_total=4, client_num_per_round=4, comm_round=6, epochs=2,
    batch_size=16, learning_rate=0.05,
)), should_init_logs=False)
ds, od = data_mod.load(args)
bundle = model_mod.create(args, od)
res = FedMLRunner(args, fedml.get_device(args), ds, bundle).run()
print("acc:", res["test_acc"], "genotype:", res["genotype"])
