"""Federated semantic segmentation with mIoU reporting."""

# run-from-checkout shim: make the repo importable without `pip install -e .`
import os as _os, sys as _sys
_sys.path.insert(0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), "..")))

import fedml_tpu as fedml
from fedml_tpu import data as data_mod, models as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.runner import FedMLRunner

args = fedml.init(Arguments(overrides=dict(
    dataset="pascal_voc", model="fcn", federated_optimizer="FedSeg",
    client_num_in_total=4, client_num_per_round=4, comm_round=2, epochs=1,
    batch_size=8, learning_rate=0.05, seg_model_width=16,
)), should_init_logs=False)
ds, od = data_mod.load(args)
bundle = model_mod.create(args, od)
print(FedMLRunner(args, fedml.get_device(args), ds, bundle).run())
