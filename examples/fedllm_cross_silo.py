"""FedLLM: cross-silo federated fine-tuning of the Cheetah transformer.

The two product pillars meeting (the reference ships each half separately —
Octopus cross-silo FL and an EMPTY Cheetah stub at
``python/fedml/distributed/``): two organizations fine-tune one
Llama-architecture LM without sharing data. Each silo's local steps run
mesh-sharded (``parallel.train_step.CheetahTrainer``); rounds ride the
cross-silo FSM with bulk weights on the payload store.
"""

# run-from-checkout shim: make the repo importable without `pip install -e .`
import os as _os, sys as _sys
_sys.path.insert(0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), "..")))

import tempfile
import threading
import time

import fedml_tpu as fedml
from fedml_tpu import data as data_mod, models as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.cross_silo import FedMLCrossSiloClient, FedMLCrossSiloServer

store = tempfile.mkdtemp(prefix="fedllm_store_")


def make_args(role, rank=0):
    return fedml.init(Arguments(overrides=dict(
        training_type="cross_silo", dataset="shakespeare", model="cheetah",
        model_size="tiny", role=role, rank=rank, run_id="fedllm-example",
        client_num_in_total=2, client_num_per_round=2, comm_round=2,
        local_steps=4, batch_size=8, learning_rate=0.05,
        client_optimizer="adam", backend="LOOPBACK",
        payload_store_dir=store, payload_inline_limit_bytes=4096,
    )), should_init_logs=False)


args = make_args("server")
ds, od = data_mod.load(args)
bundle = model_mod.create(args, od)
server = FedMLCrossSiloServer(args, None, ds, bundle)

clients = [
    FedMLCrossSiloClient(make_args("client", rank=r), None, ds, bundle)
    for r in (1, 2)
]
threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
for t in threads:
    t.start()
time.sleep(0.1)
result = server.run()
for t in threads:
    t.join(timeout=60)
print({"fedllm": result,
       "params_m": bundle.param_count(
           server.manager.global_params) / 1e6})
