"""The whole FedAvg optimizer family on the fused sp engine."""

# run-from-checkout shim: make the repo importable without `pip install -e .`
import os as _os, sys as _sys
_sys.path.insert(0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), "..")))

import fedml_tpu as fedml
from fedml_tpu import data as data_mod, models as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.runner import FedMLRunner

for opt in ("FedAvg", "FedProx", "FedOpt", "FedNova", "SCAFFOLD", "FedSGD"):
    args = fedml.init(Arguments(overrides=dict(
        dataset="synthetic", model="lr", federated_optimizer=opt,
        client_num_in_total=16, client_num_per_round=8, comm_round=5,
        epochs=1, batch_size=16, learning_rate=0.1,
    )), should_init_logs=False)
    ds, od = data_mod.load(args)
    bundle = model_mod.create(args, od)
    res = FedMLRunner(args, fedml.get_device(args), ds, bundle).run()
    print(f"{opt:10s} acc={res['test_acc']:.3f}")
