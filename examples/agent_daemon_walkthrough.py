"""Agent-daemon walkthrough: build → submit → claim → run → status FSM.

The deployment plane end-to-end on one host (reference:
``cli/edge_deployment/client_runner.py`` + daemons — there the queue is the
MLOps MQTT broker; here it is a directory both submitter and agent see,
which is what a TPU pod actually shares):

1. ``fedml_tpu build`` packages a training entry point;
2. ``submit_job`` drops it into the job queue (atomic descriptor publish);
3. an ``Agent`` claims it (atomic rename — safe with many agents), unpacks,
   runs the entry point as a subprocess, and appends every status
   transition to ``status.jsonl`` (IDLE → UPGRADING → INITIALIZING →
   TRAINING → FINISHED, the reference's client_constants FSM).
"""

# run-from-checkout shim: make the repo importable without `pip install -e .`
import os as _os, sys as _sys
_sys.path.insert(0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), "..")))

import json
import os
import tempfile

from fedml_tpu.agent import Agent, agent_state, login, submit_job
from fedml_tpu.cli import main as cli_main

root = tempfile.mkdtemp(prefix="agent-demo-")
src = os.path.join(root, "src")
os.makedirs(src)
with open(os.path.join(src, "train.py"), "w") as f:
    f.write("print('hello from the federated job')\n")

# 1. build the package (the `fedml_tpu build` CLI)
pkg = os.path.join(root, "pkg.zip")
rc = cli_main(["build", "-sf", src, "-ep", "train.py", "-o", pkg])
assert rc == 0, "build failed"

# 2. bind this host as an edge device (local state, reference `fedml login`)
state_dir = os.path.join(root, "state")
login("acct-42", role="client", state_dir=state_dir)
print("agent state:", agent_state(state_dir))

# 3. submit into the shared-directory queue + run one agent cycle
jobs = os.path.join(root, "jobs")
job_id = submit_job(pkg, jobs)
agent = Agent(jobs_dir=jobs, work_dir=os.path.join(root, "work"))
result = agent.run_once()
assert result is not None and result.job_id == job_id

# 4. the observable status FSM (work_dir/status.jsonl)
transitions = agent.job_statuses(job_id)
print("job", job_id, "→", " → ".join(transitions))
assert transitions[-1] == "FINISHED", transitions
print("agent walkthrough ok")
