"""GPipe pipeline parallelism: 2 stages (needs >= 2 devices; on one host
set XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu)."""

# run-from-checkout shim: make the repo importable without `pip install -e .`
import os as _os, sys as _sys
_sys.path.insert(0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), "..")))

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.parallel.pipeline import PipelineCheetah, microbatch
from fedml_tpu.parallel.sharding import make_mesh
from fedml_tpu.parallel.transformer import TransformerConfig

if len(jax.devices()) < 2:
    raise SystemExit("need >= 2 devices for pipeline parallelism")

cfg = TransformerConfig(vocab_size=256, d_model=128, n_layers=4, n_heads=4,
                        n_kv_heads=4, d_ff=384, max_seq_len=64, remat=False)
mesh = make_mesh({"pipeline": 2}, devices=jax.devices()[:2])
pp = PipelineCheetah(cfg, mesh, microbatches=4, optimizer=optax.adamw(1e-3))
params = pp.init_params(jax.random.PRNGKey(0))
opt = pp.init_opt_state(params)
rng = np.random.RandomState(0)
tok = rng.randint(0, 256, (8, 64)).astype(np.int32)
mt, mm = microbatch(tok, np.ones_like(tok), 4)
for step in range(10):
    params, opt, loss = pp.train_step(params, opt, jnp.asarray(mt), jnp.asarray(mm))
    print(f"step {step}: loss={float(loss):.4f}")
