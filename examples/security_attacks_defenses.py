"""Byzantine clients vs robust aggregation: attack degrades plain FedAvg,
the defense recovers it."""

# run-from-checkout shim: make the repo importable without `pip install -e .`
import os as _os, sys as _sys
_sys.path.insert(0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), "..")))

import fedml_tpu as fedml
from fedml_tpu import data as data_mod, models as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.runner import FedMLRunner


def run(**kw):
    base = dict(dataset="synthetic", model="lr", client_num_in_total=10,
                client_num_per_round=10, comm_round=6, epochs=1,
                batch_size=16, learning_rate=0.1)
    base.update(kw)
    args = fedml.init(Arguments(overrides=base), should_init_logs=False)
    ds, od = data_mod.load(args)
    bundle = model_mod.create(args, od)
    return FedMLRunner(args, fedml.get_device(args), ds, bundle).run()


clean = run()
attacked = run(enable_attack=True, attack_type="byzantine_random",
               byzantine_client_frac=0.3, byzantine_scale=10.0)
defended = run(enable_attack=True, attack_type="byzantine_random",
               byzantine_client_frac=0.3, byzantine_scale=10.0,
               enable_defense=True, defense_type="krum",
               byzantine_client_num=3)
print(f"clean    acc={clean['test_acc']:.3f}")
print(f"attacked acc={attacked['test_acc']:.3f}")
print(f"defended acc={defended['test_acc']:.3f}")
