"""Cross-silo FL over gRPC with server + clients as SEPARATE OS processes.

The deployment shape the reference's ``grpc_fedavg_mnist_lr_example`` runs
(one process per organization, DCN between them), on this framework's
single gRPC backend — with the r5 direct-tensor wire format on
(``grpc_wire_format: raw``): zero-copy tensor frames, chunked streaming
for bulk payloads (``core/distributed/tensor_transport.py``).

The script re-execs itself for the client roles, so one file is the whole
multi-process world:  python cross_silo_grpc_multiprocess.py
"""

# run-from-checkout shim: make the repo importable without `pip install -e .`
import os as _os, sys as _sys
_sys.path.insert(0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), "..")))

import socket
import subprocess
import sys

import fedml_tpu as fedml
from fedml_tpu import data as data_mod, models as model_mod
from fedml_tpu.arguments import Arguments

N_CLIENTS = 2


def mk(role, rank, port):
    return fedml.init(Arguments(overrides=dict(
        training_type="cross_silo", dataset="synthetic", model="lr",
        client_num_in_total=N_CLIENTS, client_num_per_round=N_CLIENTS,
        comm_round=3, epochs=2, batch_size=8, learning_rate=0.2,
        backend="GRPC", comm_port=port, comm_host="127.0.0.1",
        grpc_wire_format="raw",  # direct-tensor frames + streaming
        role=role, rank=rank, run_id="grpc-mp-demo",
    )), should_init_logs=False)


def main() -> None:
    if "--client" in sys.argv:
        rank = int(sys.argv[sys.argv.index("--client") + 1])
        port = int(sys.argv[sys.argv.index("--port") + 1])
        from fedml_tpu.cross_silo import FedMLCrossSiloClient

        args = mk("client", rank, port)
        ds, od = data_mod.load(args)
        FedMLCrossSiloClient(args, None, ds, model_mod.create(args, od)).run()
        return

    # parent = the server org; pick a free base port for the world
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    from fedml_tpu.cross_silo import FedMLCrossSiloServer

    args = mk("server", 0, port)
    ds, od = data_mod.load(args)
    server = FedMLCrossSiloServer(args, None, ds, model_mod.create(args, od))
    procs = [
        subprocess.Popen([sys.executable, __file__, "--client", str(r),
                          "--port", str(port)])
        for r in range(1, N_CLIENTS + 1)
    ]
    ok = False
    try:
        result = server.run()
        print("grpc multiprocess result:", result)
        assert result is not None and result["test_acc"] > 0.5
        ok = True
    finally:
        for p in procs:
            if not ok:
                p.kill()  # don't orphan clients (or mask the real error
                #           with TimeoutExpired) when the server failed
            p.wait(timeout=60)
    print("cross-silo gRPC multi-process ok")


if __name__ == "__main__":
    main()
