"""Cohort sharded over every local chip (`clients` mesh axis)."""

# run-from-checkout shim: make the repo importable without `pip install -e .`
import os as _os, sys as _sys
_sys.path.insert(0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), "..")))

import fedml_tpu as fedml
from fedml_tpu import data as data_mod, models as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.runner import FedMLRunner

args = fedml.init(Arguments(overrides=dict(
    training_type="simulation", backend="mesh", dataset="synthetic",
    model="cnn" if False else "lr", client_num_in_total=16,
    client_num_per_round=8, comm_round=5, epochs=1, batch_size=16,
    learning_rate=0.1,
)), should_init_logs=False)
ds, od = data_mod.load(args)
bundle = model_mod.create(args, od)
print(FedMLRunner(args, fedml.get_device(args), ds, bundle).run())
