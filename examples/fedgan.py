"""Federated GAN: both nets averaged every round."""

# run-from-checkout shim: make the repo importable without `pip install -e .`
import os as _os, sys as _sys
_sys.path.insert(0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), "..")))

import fedml_tpu as fedml
from fedml_tpu import data as data_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.simulation.fedgan_api import FedGanAPI

args = fedml.init(Arguments(overrides=dict(
    dataset="synthetic", model="lr", federated_optimizer="FedGAN",
    client_num_in_total=4, client_num_per_round=4, comm_round=8, epochs=3,
    batch_size=16, learning_rate=2e-3,
)), should_init_logs=False)
ds, _ = data_mod.load(args)
api = FedGanAPI(args, None, ds)
print(api.train())
print("samples:", api.sample(4).shape)
