"""Long-context pretraining: exact ring attention over a sequence mesh axis.

The sequence axis shards the TOKEN dimension across chips: each device holds
L/n tokens of every sample, attention runs blockwise with flash-style
running (m, l, o) accumulators, and K/V blocks rotate around the ring over
``lax.ppermute`` (``parallel/ring_attention.py``) — exact causal attention,
no approximation, with per-chip memory O(L/n) instead of O(L). This is how
a context longer than one chip's HBM trains. (reference has no analog —
SURVEY.md §2.5 lists sequence parallelism as absent upstream; new capability.)

This demo self-provisions a 4-device virtual CPU mesh (sequence=4), trains a
4k-token context — 1k tokens resident per device (shapes sized for the
single-core demo host; scale SEQ freely on real chips) — and checks the
loss is finite and decreasing. The SAME program runs on a real pod slice by
removing the virtual-platform lines.

Run: ``python long_context_ring_attention.py`` (~10 min on one host core —
almost all XLA:CPU compile; seconds per step on real chips).
"""

# run-from-checkout shim: make the repo importable without `pip install -e .`
import os as _os, sys as _sys
_sys.path.insert(0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), "..")))

import os

# virtual 4-device platform — must happen before jax backend init
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from fedml_tpu.parallel.sharding import make_mesh  # noqa: E402
from fedml_tpu.parallel.train_step import (  # noqa: E402
    CheetahTrainer,
    make_optimizer,
)
from fedml_tpu.parallel.transformer import TransformerConfig  # noqa: E402

# 1k tokens resident per device on the 4-way sequence mesh. These shapes
# are sized for the single-core CPU demo host — on real chips scale SEQ to
# hundreds of thousands of tokens; per-device memory stays O(SEQ/4)
SEQ = 4096

cfg = TransformerConfig(
    vocab_size=512, d_model=64, n_layers=2, n_heads=4, n_kv_heads=4,
    d_ff=192, max_seq_len=SEQ, remat=True,
)
mesh = make_mesh({"sequence": 4})
trainer = CheetahTrainer(
    cfg, mesh, optimizer=make_optimizer(3e-3, warmup_steps=2, total_steps=20)
)
state = trainer.init_state(jax.random.PRNGKey(0))

rng = np.random.RandomState(0)
# learnable stream: tokens repeat with period 7, so next-token loss can
# drop well below log(vocab) within a few steps
base = rng.randint(0, cfg.vocab_size, size=7)
tokens = jnp.asarray(np.tile(base, SEQ // 7 + 1)[:SEQ][None, :].astype(np.int32))
mask = jnp.ones((1, SEQ), jnp.int32)

losses = []
for step in range(4):
    state, metrics = trainer.train_step(state, tokens, mask)
    losses.append(float(np.asarray(metrics["loss"])))
    print(f"step {step}: loss {losses[-1]:.3f} "
          f"({SEQ} tokens, {SEQ // 4} resident/device)", flush=True)

assert np.isfinite(losses).all()
assert losses[-1] < losses[0], (losses[0], losses[-1])
print(f"ring attention over {SEQ} tokens on a sequence=4 mesh: "
      f"loss {losses[0]:.2f} -> {losses[-1]:.2f}")
