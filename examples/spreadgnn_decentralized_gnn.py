"""SpreadGNN: serverless (decentralized) federated GNN training.

reference: ``research/SpreadGNN/`` — decentralized federated molecular GNN:
clients hold disjoint molecule graphs, there is NO server, and models mix
over a communication topology (periodic averaging with neighbors).

TPU re-grounding: the two pieces already exist as orthogonal engines and
compose directly — the FedGraphNN packed-dense-block models
(``models/gnn.py``) ride the decentralized gossip engine
(``simulation/decentralized_api.py``: local SGD + one mixing-matrix matmul
per round over the ring topology) untouched. That composition IS SpreadGNN:
graph learning + serverless mixing.

Run: ``python spreadgnn_decentralized_gnn.py``.
"""

# run-from-checkout shim: make the repo importable without `pip install -e .`
import os as _os, sys as _sys
_sys.path.insert(0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), "..")))

import fedml_tpu as fedml
from fedml_tpu import data as data_mod, models as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.runner import FedMLRunner

args = fedml.init(Arguments(overrides=dict(
    dataset="moleculenet_clf", model="gcn",
    federated_optimizer="decentralized_fl",
    client_num_in_total=8, client_num_per_round=8, comm_round=10, epochs=2,
    batch_size=16, learning_rate=0.05, topology="ring",
    topology_neighbor_num=2,
)), should_init_logs=False)
ds, od = data_mod.load(args)
bundle = model_mod.create(args, od)
res = FedMLRunner(args, fedml.get_device(args), ds, bundle).run()
print(f"SpreadGNN (decentralized molecule GNN): acc={res['test_acc']:.3f} "
      f"loss={res['test_loss']:.3f}")
