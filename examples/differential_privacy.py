"""Central and local DP on the same engine."""

# run-from-checkout shim: make the repo importable without `pip install -e .`
import os as _os, sys as _sys
_sys.path.insert(0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), "..")))

import fedml_tpu as fedml
from fedml_tpu import data as data_mod, models as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.runner import FedMLRunner

for dp_type in ("cdp", "ldp"):
    args = fedml.init(Arguments(overrides=dict(
        dataset="synthetic", model="lr", client_num_in_total=16,
        client_num_per_round=8, comm_round=5, epochs=1, batch_size=16,
        learning_rate=0.1, enable_dp=True, dp_type=dp_type, epsilon=50.0,
        delta=1e-5, clipping_norm=5.0, mechanism_type="gaussian",
    )), should_init_logs=False)
    ds, od = data_mod.load(args)
    bundle = model_mod.create(args, od)
    res = FedMLRunner(args, fedml.get_device(args), ds, bundle).run()
    print(f"{dp_type} acc={res['test_acc']:.3f}")
