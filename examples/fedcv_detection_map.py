"""Federated object detection with mAP@0.5 (FedCV detection family).

reference: ``python/app/fedcv/object_detection`` — YOLOv5 federated
fine-tuning with mAP eval. Here: the dense anchor-free CenterNet head
trains through the sp engine, and evaluation is true detection decoding
(3x3 peak NMS + top-k) scored with VOC-style mAP@0.5/@0.25
(``ml/detection_metrics.py``) — not just per-center class accuracy. Staging
a COCO-format dataset (annotations json + images dir) under
``data_cache_dir`` swaps the synthetic rectangles for real images via
``data/real_readers.try_load_coco_detection``.
"""

# run-from-checkout shim: make the repo importable without `pip install -e .`
import os as _os, sys as _sys
_sys.path.insert(0, _os.path.abspath(_os.path.join(_os.path.dirname(__file__), "..")))

import fedml_tpu as fedml
from fedml_tpu import data as data_mod, models as model_mod
from fedml_tpu.arguments import Arguments
from fedml_tpu.ml.detection_metrics import (
    collect_detection_logits, map_at_50,
)
from fedml_tpu.simulation.sp_api import FedAvgAPI

args = fedml.init(Arguments(overrides=dict(
    dataset="coco128_det", model="centernet", client_num_in_total=4,
    client_num_per_round=4, comm_round=6, epochs=2, batch_size=8,
    learning_rate=3e-3, client_optimizer="adam", frequency_of_the_test=100,
)), should_init_logs=False)
ds, od = data_mod.load(args)
bundle = model_mod.create(args, od)
api = FedAvgAPI(args, fedml.get_device(args), ds, bundle)

for r in range(int(args.comm_round)):
    args.round_idx = r
    api._train_round(r)

# ONE forward over the test set; score the same logits at both IoUs
import numpy as np

logits = collect_detection_logits(bundle, api.global_params, ds.test_x)
targets = [np.asarray(t, np.float32) for t in ds.test_y]
m50 = map_at_50(logits, targets)
m25 = map_at_50(logits, targets, iou_thresh=0.25)
print(f"federated detection: mAP@0.5={m50['map50']:.3f} "
      f"mAP@0.25={m25['map50']:.3f} over {m50['total_gt']:.0f} GT boxes")
assert m25["map50"] > 0.05, "no localization signal"
print("fedcv detection mAP example ok")
