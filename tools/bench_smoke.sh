#!/usr/bin/env bash
# Smoke-check the bench harness itself: a 2-round lr leg on XLA:CPU through
# the FULL orchestrator (probe -> leg subprocess -> cumulative JSON line),
# under a hard 120 s timeout. Guards the one failure mode that zeroed round 4
# (rc=124 with an empty tail): whatever happens, the bench must exit 0-ish
# fast and leave a parseable JSON tail.
#
# The leg runs its telemetry pass into BENCH_TRACKING_DIR, so this also
# asserts the observability contract: the tracked leg leaves a JSONL event
# log that read_events round-trips (with per-round RoundRecords) and a
# parseable Prometheus metrics exposition, and the bench line carries the
# per-phase breakdown.
#
# Usage: tools/bench_smoke.sh          (CI: exits non-zero on any regression)
set -uo pipefail
cd "$(dirname "$0")/.."

track_dir=$(mktemp -d /tmp/fedml_bench_smoke_track.XXXXXX)
trap 'rm -rf "$track_dir"' EXIT

out=$(timeout -k 10 240 env \
    BENCH_PLATFORM=cpu \
    BENCH_SMOKE=1 \
    BENCH_LEGS=fedavg,fedavg_million_client,fedavg_compressed_round,fedavg_wire \
    BENCH_REGISTRY_N=20000 \
    BENCH_COHORT_K=256 \
    BENCH_WIRE_DIM=262144 \
    BENCH_WIRE_REPS=3 \
    BENCH_BUDGET_S=220 \
    BENCH_MIN_LEG_S=5 \
    BENCH_LEG_TIMEOUT_S=100 \
    BENCH_CACHE_TTL_S=0 \
    BENCH_TRACKING_DIR="$track_dir" \
    python bench.py 2>/dev/null)
rc=$?

if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
    echo "bench_smoke: FAIL — bench hit the hard timeout (rc=$rc)" >&2
    exit 1
fi
if [ "$rc" -ne 0 ]; then
    echo "bench_smoke: FAIL — bench exited rc=$rc" >&2
    exit 1
fi

tail_line=$(printf '%s\n' "$out" | tail -n 1)
TRACK_DIR="$track_dir" python - "$tail_line" <<'EOF'
import json
import os
import sys

line = json.loads(sys.argv[1])
assert line["metric"] == "fedavg_rounds_per_sec_100clients_cifar10_resnet56", line
# the CPU smoke leg must have completed (not errored, not skipped)
ok = ("fedavg_cpu_smoke_rounds_per_sec" in line
      and "fedavg_error" not in line
      and "fedavg_skipped" not in line)
assert ok, f"fedavg smoke leg did not complete: {line}"

# telemetry contract: the tracked pass produced a per-phase breakdown...
assert line.get("fedavg_phases"), f"no phase breakdown in line: {line}"
assert line.get("fedavg_phase_rounds", 0) > 0, line

# ...a JSONL event log that read_events round-trips, with RoundRecords...
from fedml_tpu.core.mlops import read_events

track_dir = os.environ["TRACK_DIR"]
logs = [f for f in os.listdir(track_dir) if f.endswith(".jsonl")]
assert logs, f"no JSONL event log in {track_dir}"
events = read_events(os.path.join(track_dir, logs[0]))
records = [e for e in events if e.get("kind") == "round_record"]
assert records, f"no round_record events in {logs[0]}"

# ...and a parseable Prometheus metrics exposition
metrics_path = os.path.join(track_dir, "metrics.prom")
assert os.path.exists(metrics_path), f"no metrics file at {metrics_path}"
samples = 0
with open(metrics_path) as f:
    for raw in f:
        raw = raw.strip()
        if not raw or raw.startswith("#"):
            continue
        name, value = raw.rsplit(" ", 1)
        float(value)  # every sample line must parse
        samples += 1
assert samples > 0, "metrics exposition is empty"

# resume-overhead probe (BENCH_RESUME defaults on under BENCH_SMOKE):
# restart-to-first-dispatch must be present and sane so checkpoint-cadence
# tuning stays data-driven (docs/robustness.md)
assert "fedavg_resume_overhead_s" in line, f"no resume probe in line: {line}"
assert 0 < line["fedavg_resume_overhead_s"] < 120, line

# registry leg (fedml_tpu/scale/, scaled down to N=20k / K=256): the
# cohort substrate must sustain registry-scale rounds with ZERO
# steady-state compiles (cohort resampling is recompile-free by
# construction) and a measured prefetch overlap > 0 (docs/scale.md)
assert "fedavg_million_client_error" not in line, line
assert "fedavg_million_client_skipped" not in line, line
assert line.get("million_rounds_per_sec", 0) > 0, line
assert line.get("million_steady_compiles", -1) == 0, line
assert line.get("million_prefetch_overlap", 0) > 0, line
assert line.get("million_registry_n") == 20000, line

# delta-delivery leg (fedml_tpu/delivery/, docs/delivery.md): the delta
# path must ENGAGE (frames + decodes on the wire) and steady-state
# comm.bytes must drop >= 10x at parity accuracy (ISSUE 9 acceptance)
assert "fedavg_compressed_round_error" not in line, line
assert "fedavg_compressed_round_skipped" not in line, line
assert line.get("compressed_s2c_delta_frames", 0) > 0, line
assert line.get("compressed_c2s_delta_decodes", 0) > 0, line
assert line.get("compressed_reduction_x", 0) >= 10.0, line
acc_drop = line.get("uncompressed_acc", 1) - line.get("compressed_acc", 0)
assert acc_drop <= 0.05, f"accuracy not at parity: {line}"

# device-direct wire leg (fedml_tpu/delivery/device_codec.py, docs/
# delivery.md): the device kernels must ENGAGE (nonzero device encodes +
# decodes, zero host fallbacks in the soak) and the frames must be
# byte-identical to the host codec (the leg raises on divergence, so
# wire_parity present+true == the gate actually ran)
assert "fedavg_wire_error" not in line, line
assert "fedavg_wire_skipped" not in line, line
assert line.get("wire_parity") is True, line
assert line.get("wire_soak_ok") is True, line
assert line.get("wire_soak_device_encodes", 0) > 0, line
assert line.get("wire_soak_device_decodes", 0) > 0, line
assert line.get("wire_soak_host_fallbacks", -1) == 0, line
assert line.get("wire_host_cpu_ms_per_mb", {}).get("device_delta", 0) > 0, \
    line

print("bench_smoke: OK —",
      f"{line['fedavg_cpu_smoke_rounds_per_sec']:.2f} rounds/s,",
      f"compile {line.get('fedavg_compile_s', '?')}s,",
      f"fused={line.get('fedavg_round_fused')},",
      f"resume {line['fedavg_resume_overhead_s']:.2f}s,",
      f"registry {line['million_registry_n']}cl",
      f"@ {line['million_rounds_per_sec']:.2f} rounds/s",
      f"(overlap {line['million_prefetch_overlap']:.2f}),",
      f"delta {line['compressed_reduction_x']:.1f}x bytes",
      f"(acc {line['compressed_acc']:.3f} vs"
      f" {line['uncompressed_acc']:.3f}),",
      f"wire {line['wire_host_cpu_reduction_x']:.1f}x host-CPU",
      f"({line['wire_soak_device_encodes']} dev encodes),",
      f"{len(records)} round records, {samples} metric samples")
EOF
