#!/usr/bin/env bash
# Smoke-check the bench harness itself: a 2-round lr leg on XLA:CPU through
# the FULL orchestrator (probe -> leg subprocess -> cumulative JSON line),
# under a hard 120 s timeout. Guards the one failure mode that zeroed round 4
# (rc=124 with an empty tail): whatever happens, the bench must exit 0-ish
# fast and leave a parseable JSON tail.
#
# Usage: tools/bench_smoke.sh          (CI: exits non-zero on any regression)
set -uo pipefail
cd "$(dirname "$0")/.."

out=$(timeout -k 10 120 env \
    BENCH_PLATFORM=cpu \
    BENCH_SMOKE=1 \
    BENCH_LEGS=fedavg \
    BENCH_BUDGET_S=110 \
    BENCH_MIN_LEG_S=5 \
    BENCH_LEG_TIMEOUT_S=100 \
    BENCH_CACHE_TTL_S=0 \
    python bench.py 2>/dev/null)
rc=$?

if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
    echo "bench_smoke: FAIL — bench hit the hard timeout (rc=$rc)" >&2
    exit 1
fi
if [ "$rc" -ne 0 ]; then
    echo "bench_smoke: FAIL — bench exited rc=$rc" >&2
    exit 1
fi

tail_line=$(printf '%s\n' "$out" | tail -n 1)
python - "$tail_line" <<'EOF'
import json
import sys

line = json.loads(sys.argv[1])
assert line["metric"] == "fedavg_rounds_per_sec_100clients_cifar10_resnet56", line
# the CPU smoke leg must have completed (not errored, not skipped)
ok = ("fedavg_cpu_smoke_rounds_per_sec" in line
      and "fedavg_error" not in line
      and "fedavg_skipped" not in line)
assert ok, f"fedavg smoke leg did not complete: {line}"
print("bench_smoke: OK —",
      f"{line['fedavg_cpu_smoke_rounds_per_sec']:.2f} rounds/s,",
      f"compile {line.get('fedavg_compile_s', '?')}s,",
      f"fused={line.get('fedavg_round_fused')}")
EOF
