"""graftshard rule registry (S001–S005), merged into the shared graftlint
Finding infrastructure so all three suites render/baseline/JSON identically."""

from __future__ import annotations

from typing import Dict, Tuple

from ..graftlint.findings import Finding, register_rules

# rule id -> (title, autofix hint)
SHARD_RULES: Dict[str, Tuple[str, str]] = {
    "S001": (
        "partition-rule-coverage-gap",
        "end the rule set with an explicit `.*=` catch-all (replicate or "
        "shard — but say which); a leaf no rule matches silently takes the "
        "fallback, and a silently replicated 7B embedding is an OOM on "
        "every chip at once",
    ),
    "S002": (
        "invalid-partition-spec",
        "name only axes the mesh actually has (constants.MESH_AXIS_*), "
        "never repeat an axis inside one PartitionSpec, and keep every "
        "sharded dimension divisible by the product of its axis extents — "
        "XLA pads indivisible shards per-device and the HBM math lies",
    ),
    "S003": (
        "implicit-reshard-on-hot-path",
        "keep one sharding per value across the traced region: hoist "
        "device_put out of jit'd code, and constrain both operands of a "
        "cross-spec op to ONE layout before combining them — a spec "
        "mismatch lowers to a hidden all-gather every step",
    ),
    "S004": (
        "host-transfer-of-sharded-array",
        "keep sharded values on device: reduce on-device and pull one "
        "scalar after the loop, or use per-shard views — np.asarray/"
        "device_get/.item() on a sharded array gathers every shard over "
        "ICI to one host, once per iteration",
    ),
    "S005": (
        "hbm-budget-exceeded",
        "shard the state further (grow the fsdp/tensor axes), shrink the "
        "per-device batch, or drop mu_dtype to bfloat16 — the static "
        "budget already exceeds the chip before activations are counted",
    ),
}

register_rules(SHARD_RULES)

__all__ = ["Finding", "SHARD_RULES"]
