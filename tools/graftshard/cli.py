"""graftshard CLI: ``python -m tools.graftshard [paths...]``.

Thin suite definition over the shared driver
(:mod:`tools.graftlint.clikit` — flags, baseline handling, rendering, and
the exit-code contract live there, shared with graftlint/graftproto).
Exit codes: 0 clean (after baseline + pragmas), 1 findings, 2 usage error
OR analyzer crash — that includes crashes inside the HBM estimator and the
``--runtime`` trace pass.

Extras over the sibling suites:

- ``--model NAME [--mesh SPEC]`` — run the S005 static HBM-budget
  estimator (per-device byte totals against the v5e/v5p/CPU table, no
  hardware; the report rides the JSON payload under ``"hbm"`` and renders
  after the findings in text mode);
- ``--check-rules`` / ``--check-state-rules`` — validate an operator rule
  set (the ``--mesh_partition_rules`` syntax) for catch-all coverage and
  axis validity before a run ever ships it;
- ``--runtime`` — trace the real mesh_api/cheetah factories over a forced
  multi-device CPU mesh and diff declared vs inferred shardings.
"""

from __future__ import annotations

import argparse
from typing import Dict, List, Optional, Tuple

from ..graftlint import clikit
from ..graftlint.findings import Finding
from .analyzer import DEFAULT_BASELINE_RELPATH, analyze_paths_with_model
from .findings import SHARD_RULES


def _add_arguments(p: argparse.ArgumentParser) -> None:
    p.add_argument("--runtime", action="store_true",
                   help="also trace the real mesh/cheetah factories over "
                        "a forced multi-device CPU mesh and diff declared "
                        "vs inferred shardings (imports jax)")
    p.add_argument("--model", default="",
                   help="run the S005 HBM-budget estimator for this model "
                        "registry entry (e.g. 7b, tiny); imports jax")
    p.add_argument("--mesh", default="4x4",
                   help="mesh rows for --model: comma list of "
                        "[chip:]shape — '4x4' (16 chips on fsdp), "
                        "'v5e:2x4', 'fsdp=8+tensor=2'; chipless rows are "
                        "priced against every chip (default: 4x4)")
    p.add_argument("--seq-len", type=int, default=0,
                   help="sequence length for the HBM batch term "
                        "(default: the model config's max_seq_len)")
    p.add_argument("--batch-per-device", type=int, default=1)
    p.add_argument("--mu-dtype", default="bfloat16",
                   choices=("float32", "bfloat16"),
                   help="adam first-moment dtype for the HBM optimizer "
                        "term (default bfloat16, matching the 7B rows)")
    p.add_argument("--check-rules", default="",
                   help="validate a --mesh_partition_rules string (S001 "
                        "catch-all + S002 axis validity), e.g. "
                        "'cohort/.*=clients;.*='")
    p.add_argument("--check-state-rules", default="",
                   help="validate a --mesh_state_rules string the same way")


def _check_rule_string(text: str, which: str,
                       vocabulary: frozenset) -> List[Finding]:
    """Operator rule-set validation (the CLI/YAML surface of S001/S002).

    Axis names validate against the SAME vocabulary the AST pass built
    from the scanned tree (MESH_AXIS_* constants + Mesh construction
    sites), so a legitimately declared private axis like ``silo_dp`` is
    not falsely rejected here."""
    from fedml_tpu.scale.partition_rules import parse_partition_rules

    from .model import is_catch_all

    try:
        rules = parse_partition_rules(text)
    except ValueError as e:
        raise clikit.SuiteUsageError(f"--{which}: {e}") from e
    findings: List[Finding] = []
    catch_idx = next((i for i, (pat, _spec) in enumerate(rules)
                      if is_catch_all(pat)), None)
    if catch_idx is None:
        findings.append(Finding(
            rule="S001", path=f"<--{which}>", line=1, col=0,
            message=f"rule set {text!r} has no catch-all — leaves no "
                    "pattern matches silently take the fallback "
                    "(replicate); end it with an explicit '.*=' rule",
            line_text=f"rules::{which}::{text}"))
    elif catch_idx != len(rules) - 1:
        findings.append(Finding(
            rule="S001", path=f"<--{which}>", line=1, col=0,
            message=f"rule set {text!r}: catch-all "
                    f"{rules[catch_idx][0]!r} at position {catch_idx} "
                    "shadows every later rule (first match wins) — move "
                    "it last",
            line_text=f"rules::{which}::shadow::{text}"))
    for pat, spec in rules:
        for dim in spec:
            for ax in (dim if isinstance(dim, tuple) else (dim,)):
                if ax is not None and ax not in vocabulary:
                    findings.append(Finding(
                        rule="S002", path=f"<--{which}>", line=1, col=0,
                        message=f"rule {pat!r} names axis {ax!r}, which "
                                "is not a known mesh axis "
                                f"({', '.join(sorted(vocabulary))})",
                        line_text=f"rules::{which}::{pat}::{ax}"))
    return findings


def _analyze(args: argparse.Namespace,
             repo_root: str) -> Tuple[List[Finding], Dict]:
    if args.runtime:
        # BEFORE anything imports jax (the HBM estimator and --check-rules
        # both do): the runtime pass needs its forced CPU device count set
        # while jax is still unimported, or it sees 1 real device
        from .runtime_check import _ensure_devices

        _ensure_devices()
    findings, model = analyze_paths_with_model(args.paths,
                                               repo_root=repo_root)
    extra: Dict = {}
    for which, text in (("check-rules", args.check_rules),
                        ("check-state-rules", args.check_state_rules)):
        if text:
            import sys

            sys.path.insert(0, repo_root)
            findings = findings + _check_rule_string(text, which,
                                                     model.vocabulary)
    if args.model:
        import sys

        sys.path.insert(0, repo_root)
        from .hbm import estimate_budget, render_report

        try:
            hbm_findings, report = estimate_budget(
                args.model, args.mesh, seq_len=args.seq_len,
                batch_per_device=args.batch_per_device,
                mu_dtype=args.mu_dtype)
        except ValueError as e:
            raise clikit.SuiteUsageError(str(e)) from e
        findings = findings + hbm_findings
        extra["hbm"] = report
        if args.format != "json":
            print(render_report(report))
    if args.runtime:
        from .runtime_check import check_shard_runtime

        try:
            findings = findings + check_shard_runtime(repo_root)
        except RuntimeError as e:
            raise clikit.SuiteUsageError(str(e)) from e
    return findings, extra


def main(argv: Optional[List[str]] = None) -> int:
    return clikit.run_suite(
        argv,
        tool="graftshard",
        description="static sharding, HBM-budget & transfer verification "
                    "of the TPU execution plane: partition-rule coverage, "
                    "spec validity, implicit-reshard and host-transfer "
                    "detection, per-device HBM budgets without hardware",
        rules=SHARD_RULES,
        analyze=_analyze,
        baseline_relpath=DEFAULT_BASELINE_RELPATH,
        add_arguments=_add_arguments,
    )


if __name__ == "__main__":
    raise SystemExit(main())
