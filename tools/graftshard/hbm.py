"""S005 — static HBM-budget estimation, no hardware required.

Walks a model config × partition rules × optimizer state through
``jax.eval_shape`` and prices every leaf against an *abstract* mesh (a dict
of axis extents — no devices are touched, so a CPU-only host can budget a
v5p pod):

- params come out of ``jax.eval_shape(model.init)`` still wearing their
  ``nn.Partitioned`` logical axis names; ``logical_to_mesh_spec`` maps them
  to mesh axes exactly as the real trainer does;
- optimizer state is shaped by ``jax.eval_shape(opt.init)`` and sharded by
  the longest-path-suffix match the pipeline uses (``_opt_state_specs``) —
  adam's ``count`` scalar stays replicated, the moments follow their param;
- gradients mirror params (transient but resident at peak);
- the batch (tokens+mask) shards over the data-parallel extent.

Per-device bytes = leaf bytes ÷ ∏(extents of the axes its spec names),
with an S002 finding when a sharded dimension is not divisible by its axis
extents (XLA pads the shard; the budget then lies per-device). Totals are
compared against the chip HBM table — exceeding a requested chip's budget
is an S005 finding; the full report rides the ``--json`` payload either
way. Activations/workspace are deliberately NOT estimated: they belong to
the compiler (``tools/check_7b_readiness.py`` measures them with the real
TPU compiler's ``memory_analysis()``); S005 bounds the *state* floor, which
is what the partition rules control.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

from .findings import Finding

GiB = 1024 ** 3

# chip -> HBM bytes (None = host memory, report-only)
CHIP_HBM: Dict[str, Optional[int]] = {
    "v5e": 16 * GiB,
    "v5p": 95 * GiB,
    "cpu": None,
}

# leave headroom for XLA workspace/fragmentation, same margin as
# tools/check_7b_readiness.py applies to the compiler's own verdict
HBM_FILL_FRACTION = 0.95

_SHARDING_REL = "fedml_tpu/parallel/sharding.py"
_TRAIN_STEP_REL = "fedml_tpu/parallel/train_step.py"


def model_registry() -> Dict[str, object]:
    """--model name -> TransformerConfig factory (lazy: imports jax)."""
    from fedml_tpu.parallel.transformer import TransformerConfig

    return {
        "7b": TransformerConfig.llama2_7b,
        "llama2_7b": TransformerConfig.llama2_7b,
        "tiny": TransformerConfig.tiny,
    }


def parse_mesh_arg(text: str) -> List[Tuple[Optional[str], str, Dict[str, int]]]:
    """``--mesh`` → ``[(chip|None, label, axis extents)]``.

    Comma- or ``;``-separated entries; each is ``[chip:]shape`` where shape
    is either a topology product (``4x4`` → 16 chips, all on ``fsdp`` — the
    check_7b_readiness row convention) or explicit ``+``-joined axes
    (``fsdp=8+tensor=2``). A chipless entry is priced against every chip
    in the table.
    """
    rows: List[Tuple[Optional[str], str, Dict[str, int]]] = []
    for raw in (text or "").replace(";", ",").split(","):
        raw = raw.strip()
        if not raw:
            continue
        chip: Optional[str] = None
        shape = raw
        if ":" in raw:
            chip, _, shape = raw.partition(":")
            chip = chip.strip().lower()
            if chip not in CHIP_HBM:
                raise ValueError(
                    f"unknown chip {chip!r} in --mesh entry {raw!r} "
                    f"(known: {', '.join(sorted(CHIP_HBM))})")
        if "=" in shape:
            axes: Dict[str, int] = {}
            for part in shape.split("+"):
                name, _, n = part.partition("=")
                axes[name.strip()] = int(n)
        else:
            n = math.prod(int(d) for d in shape.lower().split("x"))
            axes = {"fsdp": n}
        rows.append((chip, shape.strip(), axes))
    if not rows:
        raise ValueError("--mesh given but empty")
    return rows


def _per_device_elems(spec, shape, axes: Dict[str, int],
                      leaf_name: str) -> Tuple[int, List[str]]:
    """(per-device element count, divisibility problems) for one leaf.

    An indivisible dimension is priced at its PADDED shard size
    (ceil(size/extent) — what XLA actually allocates per device), and
    reported as an S002 problem."""
    dims = tuple(spec)
    elems = 1
    problems: List[str] = []
    for dim_idx, size in enumerate(shape):
        dim = dims[dim_idx] if dim_idx < len(dims) else None
        extent = 1
        for ax in (dim if isinstance(dim, tuple) else (dim,)):
            if ax is not None:
                extent *= int(axes.get(ax, 1))
        if extent > 1 and size % extent:
            problems.append(
                f"{leaf_name}: dim {dim_idx} (size {size}) not divisible "
                f"by axis extent {extent} ({dim})")
        elems *= -(-int(size) // extent)  # ceil: the padded shard
    return elems, problems


def estimate_budget(model_name: str, mesh_text: str, *,
                    seq_len: int = 0, batch_per_device: int = 1,
                    mu_dtype: str = "bfloat16") -> Tuple[List[Finding], Dict]:
    """→ (findings, report dict for the ``--json`` payload)."""
    registry = model_registry()
    if model_name not in registry:
        raise ValueError(
            f"unknown --model {model_name!r} "
            f"(known: {', '.join(sorted(registry))})")

    import jax
    import jax.numpy as jnp
    from flax import linen as nn

    from fedml_tpu.parallel.pipeline import _opt_state_specs
    from fedml_tpu.parallel.sharding import logical_to_mesh_spec
    from fedml_tpu.parallel.train_step import make_optimizer
    from fedml_tpu.parallel.transformer import Transformer

    cfg = registry[model_name]()
    seq_len = int(seq_len) or int(cfg.max_seq_len)
    model = Transformer(cfg)
    dummy = jnp.zeros((1, 8), jnp.int32)
    boxed = jax.eval_shape(
        lambda r: model.init(r, dummy), jax.random.PRNGKey(0)
    )["params"]

    is_boxed = lambda x: isinstance(x, nn.Partitioned)  # noqa: E731
    leaves = []  # (name, spec, ShapeDtypeStruct)
    from jax.tree_util import tree_flatten_with_path

    flat, _ = tree_flatten_with_path(boxed, is_leaf=is_boxed)
    for path, p in flat:
        name = "/".join(_key_str(k) for k in path)
        if is_boxed(p):
            spec = logical_to_mesh_spec(p.names)
            val = p.value
        else:
            from jax.sharding import PartitionSpec as P

            spec, val = P(), p
        leaves.append((name, spec, val))

    unboxed = jax.tree.map(lambda p: p.value if is_boxed(p) else p, boxed,
                           is_leaf=is_boxed)
    opt = make_optimizer(mu_dtype=jnp.dtype(mu_dtype))
    opt_abs = jax.eval_shape(opt.init, unboxed)
    spec_by_name = {name: spec for name, spec, _v in leaves}
    p_spec = _named_spec_tree(unboxed, spec_by_name)
    o_spec = _opt_state_specs(p_spec, opt_abs)
    opt_leaves = _zip_spec_leaves(opt_abs, o_spec)

    n_params = sum(int(math.prod(v.shape)) for _n, _s, v in leaves)

    findings: List[Finding] = []
    rows = []
    for chip, label, axes in parse_mesh_arg(mesh_text):
        n_dev = math.prod(axes.values())
        div_problems: List[str] = []

        def total(entries):
            import jax.numpy as jnp

            tot = 0
            for name, spec, val in entries:
                elems, problems = _per_device_elems(spec, val.shape, axes,
                                                    name)
                div_problems.extend(problems)
                tot += elems * jnp.dtype(val.dtype).itemsize
            return tot

        params_b = total(leaves)
        grads_b = params_b  # value_and_grad mirrors the param tree
        opt_b = total(opt_leaves)
        dp = int(axes.get("data", 1)) * int(axes.get("fsdp", 1))
        batch_b = int(batch_per_device) * seq_len * 4 * 2  # tokens+mask i32
        total_b = params_b + grads_b + opt_b + batch_b

        for problem in sorted(set(div_problems)):
            findings.append(Finding(
                rule="S002", path=_SHARDING_REL, line=1, col=0,
                message=f"[{label}] {problem} — XLA pads the shard "
                        "per-device; the budget (and the step) pay for "
                        "the padded size",
                line_text=f"hbm-divisibility::{model_name}::{label}::"
                          f"{problem}"))

        for chip_name in ([chip] if chip else sorted(CHIP_HBM)):
            budget = CHIP_HBM[chip_name]
            fits = (budget is None
                    or total_b <= budget * HBM_FILL_FRACTION)
            rows.append({
                "model": model_name, "chip": chip_name, "mesh": label,
                "devices": n_dev, "axes": dict(axes),
                "params": n_params,
                "params_gib": round(params_b / GiB, 3),
                "grads_gib": round(grads_b / GiB, 3),
                "opt_gib": round(opt_b / GiB, 3),
                "batch_gib": round(batch_b / GiB, 6),
                "total_gib_per_device": round(total_b / GiB, 3),
                "hbm_gib": (round(budget / GiB, 1)
                            if budget is not None else None),
                "batch_global": int(batch_per_device) * dp,
                "fits": fits,
            })
            if not fits:
                findings.append(Finding(
                    rule="S005", path=_TRAIN_STEP_REL, line=1, col=0,
                    message=f"{model_name} on {chip_name}:{label} "
                            f"({n_dev} dev): resident state "
                            f"{total_b / GiB:.2f} GiB/device exceeds "
                            f"{HBM_FILL_FRACTION:.0%} of the chip's "
                            f"{budget / GiB:.0f} GiB HBM before any "
                            "activation is allocated",
                    line_text=f"hbm::{model_name}::{chip_name}::{label}"))

    report = {
        "model": model_name, "seq_len": seq_len,
        "batch_per_device": int(batch_per_device), "mu_dtype": mu_dtype,
        "headroom": HBM_FILL_FRACTION,
        "accounting": "params + grads + optimizer + batch (resident "
                      "state; compiler temps measured separately by "
                      "tools/check_7b_readiness.py)",
        "rows": rows,
    }
    return findings, report


def _key_str(k) -> str:
    # the one pytree-key stringifier the repo already ships — leaf names
    # here MUST match partition-rule leaf names or specs silently miss
    from fedml_tpu.scale.partition_rules import _key_name

    return _key_name(k)


def _named_spec_tree(unboxed, spec_by_name):
    """Rebuild the per-leaf spec pytree matching ``unboxed``'s structure."""
    from jax.tree_util import tree_flatten_with_path, tree_unflatten

    flat, treedef = tree_flatten_with_path(unboxed)
    out = []
    for path, _leaf in flat:
        name = "/".join(_key_str(k) for k in path)
        out.append(spec_by_name[name])
    return tree_unflatten(treedef, out)


def _zip_spec_leaves(opt_abs, o_spec):
    import jax
    from jax.sharding import PartitionSpec as P
    from jax.tree_util import tree_flatten_with_path

    flat_v, _ = tree_flatten_with_path(opt_abs)
    flat_s = jax.tree.leaves(o_spec, is_leaf=lambda x: isinstance(x, P))
    assert len(flat_v) == len(flat_s), (len(flat_v), len(flat_s))
    out = []
    for (path, val), spec in zip(flat_v, flat_s):
        name = "opt/" + "/".join(_key_str(k) for k in path)
        out.append((name, spec, val))
    return out


def render_report(report: Dict) -> str:
    lines = [
        f"HBM budget — model {report['model']} (seq {report['seq_len']}, "
        f"batch/device {report['batch_per_device']}, "
        f"mu_dtype {report['mu_dtype']})",
        f"  accounting: {report['accounting']}",
        f"  {'chip':<5} {'mesh':<14} {'dev':>4} {'params':>8} "
        f"{'grads':>8} {'opt':>8} {'total/dev':>10} {'HBM':>7}  fit",
    ]
    for r in report["rows"]:
        hbm = f"{r['hbm_gib']:.0f}G" if r["hbm_gib"] else "host"
        lines.append(
            f"  {r['chip']:<5} {r['mesh']:<14} {r['devices']:>4} "
            f"{r['params_gib']:>7.2f}G {r['grads_gib']:>7.2f}G "
            f"{r['opt_gib']:>7.2f}G {r['total_gib_per_device']:>9.2f}G "
            f"{hbm:>7}  {'OK' if r['fits'] else 'OVER'}")
    return "\n".join(lines)
