"""Sharding model: AST extraction of the TPU execution plane's GSPMD surface.

Everything is syntactic (no import of analyzed code), built on graftlint's
module index. The model captures, per scanned tree:

- **mesh-axis vocabulary** — every ``MESH_AXIS_* = "name"`` constant, plus
  the canonical axis set, so ``PartitionSpec`` axis names can be validated
  without instantiating a mesh;
- **PartitionSpec sites** — every ``P(...)`` / ``PartitionSpec(...)``
  construction, each positional dim resolved to an axis string, ``None``,
  a multi-axis tuple, or *unresolved* (dynamic expressions are skipped,
  never guessed);
- **partition-rule-set literals** — tuple/list literals of
  ``(regex, PartitionSpec)`` pairs (the ``match_partition_rules`` shape:
  ``DEFAULT_COHORT_RULES``-style in-code defaults), with their patterns, so
  S001 can prove an explicit catch-all exists.

Name resolution is deliberately shallow: a dim expression resolves through
module-level ``NAME = "literal"`` / ``NAME = constants.MESH_AXIS_X``
assignments and cross-module from-imports of those, and stops there —
anything dynamic is recorded unresolved and exempt from S002.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Tuple, Union

from ..graftlint.analyzer import FuncInfo, ModuleInfo, dotted

MESH_AXIS_PREFIX = "MESH_AXIS_"

# the canonical axis set (fedml_tpu/constants.py) — always part of the
# vocabulary so single-file scans (fixtures, editor integration) validate
# against the same axes the tree uses
CANONICAL_AXES = frozenset(
    {"clients", "data", "fsdp", "tensor", "sequence", "expert", "pipeline"}
)

# sentinel for a dim expression the resolver could not reduce to a string
UNRESOLVED = "<unresolved>"

Dim = Union[str, None, Tuple[str, ...]]


class PSpecSite:
    """One ``P(...)`` construction with resolved dims."""

    __slots__ = ("rel", "line", "dims", "func")

    def __init__(self, rel: str, line: int, dims: List[Dim],
                 func: Optional[FuncInfo]):
        self.rel = rel
        self.line = line
        self.dims = dims
        self.func = func  # enclosing function (None at module level)

    def axes(self) -> List[str]:
        """Every resolved axis string in the spec, in order (dups kept)."""
        out: List[str] = []
        for d in self.dims:
            for ax in (d if isinstance(d, tuple) else (d,)):
                if isinstance(ax, str) and ax != UNRESOLVED:
                    out.append(ax)
        return out

    def signature(self) -> Optional[Tuple]:
        """Canonical hashable layout, or None when any dim is unresolved —
        S003's cross-spec comparison only fires on fully-known layouts."""
        sig: List = []
        for d in self.dims:
            if d == UNRESOLVED or (
                    isinstance(d, tuple) and UNRESOLVED in d):
                return None
            sig.append(d)
        return tuple(sig)


class RuleSetSite:
    """A ``(regex, PartitionSpec)`` rule-set literal (in-code defaults)."""

    __slots__ = ("rel", "line", "name", "patterns")

    def __init__(self, rel: str, line: int, name: str,
                 patterns: List[Tuple[str, int]]):
        self.rel = rel
        self.line = line
        self.name = name
        self.patterns = patterns  # (pattern, line) in declaration order

    def has_catch_all(self) -> bool:
        return any(is_catch_all(p) for p, _line in self.patterns)

    def catch_all_index(self) -> Optional[int]:
        """Index of the first catch-all pattern (None if absent) — rules
        after it are dead under first-match-wins resolution."""
        for i, (p, _line) in enumerate(self.patterns):
            if is_catch_all(p):
                return i
        return None


# names a catch-all pattern must match: plain, nested, digits-only — if a
# regex search-matches all of these it matches any leaf name in practice
_CATCH_ALL_PROBES = ("w", "a/b/c", "0", "layer_7/kernel")


def is_catch_all(pattern: str) -> bool:
    try:
        pat = re.compile(pattern)
    except re.error:
        return False
    return all(pat.search(probe) is not None for probe in _CATCH_ALL_PROBES)


class ShardModel:
    def __init__(self) -> None:
        # MESH_AXIS_* attr name -> axis string (from any scanned module)
        self.axis_constants: Dict[str, str] = {}
        # axis names declared at Mesh(...) construction sites (e.g. the
        # cross-silo plane's private "silo_dp" axis)
        self.mesh_axes: set = set()
        self.pspec_sites: List[PSpecSite] = []
        self.rule_sets: List[RuleSetSite] = []

    @property
    def vocabulary(self) -> frozenset:
        return (CANONICAL_AXES
                | frozenset(self.axis_constants.values())
                | frozenset(self.mesh_axes))


# ---------------------------------------------------------------------------
# extraction
# ---------------------------------------------------------------------------


def build_model(modules: Dict[str, ModuleInfo]) -> ShardModel:
    model = ShardModel()
    _collect_axis_constants(modules, model)
    envs = {name: _module_env(mod, modules, model)
            for name, mod in modules.items()}
    for name, mod in modules.items():
        _collect_module_sites(mod, modules, model, envs[name], envs)
    return model


def _assign_parts(node: ast.AST):
    """(target, value) for simple ``x = v`` / ``x: T = v`` assignments."""
    if isinstance(node, ast.Assign) and len(node.targets) == 1:
        return node.targets[0], node.value
    if isinstance(node, ast.AnnAssign) and node.value is not None:
        return node.target, node.value
    return None, None


def _collect_axis_constants(modules: Dict[str, ModuleInfo],
                            model: ShardModel) -> None:
    for mod in modules.values():
        for node in ast.walk(mod.tree):
            target, value = _assign_parts(node)
            if not isinstance(target, ast.Name):
                continue
            if not target.id.startswith(MESH_AXIS_PREFIX):
                continue
            if (isinstance(value, ast.Constant)
                    and isinstance(value.value, str)):
                model.axis_constants[target.id] = value.value


def _module_env(mod: ModuleInfo, modules: Dict[str, ModuleInfo],
                model: ShardModel) -> Dict[str, str]:
    """Module-level NAME -> axis string, for names assigned from string
    literals or ``*.MESH_AXIS_X`` attribute reads."""
    env: Dict[str, str] = {}
    for node in mod.tree.body:
        target, value = _assign_parts(node)
        if not isinstance(target, ast.Name):
            continue
        name = target.id
        if isinstance(value, ast.Constant) and isinstance(value.value, str):
            env[name] = value.value
        elif isinstance(value, ast.Attribute):
            attr = value.attr
            if attr.startswith(MESH_AXIS_PREFIX):
                resolved = model.axis_constants.get(attr)
                if resolved is None:
                    # constants module outside the scan roots: derive from
                    # the canonical naming convention (MESH_AXIS_DATA ->
                    # "data"), which the repo's constants.py follows
                    resolved = attr[len(MESH_AXIS_PREFIX):].lower()
                env[name] = resolved
    return env


def _resolve_dim_atom(expr: ast.expr, mod: ModuleInfo,
                      env: Dict[str, str],
                      envs: Dict[str, Dict[str, str]]) -> Dim:
    if isinstance(expr, ast.Constant):
        if expr.value is None:
            return None
        if isinstance(expr.value, str):
            return expr.value
        return UNRESOLVED
    if isinstance(expr, ast.Name):
        if expr.id in env:
            return env[expr.id]
        imp = mod.from_imports.get(expr.id)
        if imp is not None:
            target_env = envs.get(imp[0])
            if target_env is not None and imp[1] in target_env:
                return target_env[imp[1]]
        return UNRESOLVED
    ds = dotted(expr)
    if ds is not None:
        attr = ds.split(".")[-1]
        if attr.startswith(MESH_AXIS_PREFIX):
            # constants.MESH_AXIS_X read directly at the P() site
            resolved = _axis_constant_anywhere(attr, envs)
            return (resolved if resolved is not None
                    else attr[len(MESH_AXIS_PREFIX):].lower())
    return UNRESOLVED


def _axis_constant_anywhere(attr: str,
                            envs: Dict[str, Dict[str, str]]
                            ) -> Optional[str]:
    for e in envs.values():
        if attr in e:
            return e[attr]
    return None


def _resolve_dim(expr: ast.expr, mod: ModuleInfo, env: Dict[str, str],
                 envs: Dict[str, Dict[str, str]]) -> Dim:
    if isinstance(expr, (ast.Tuple, ast.List)):
        parts = []
        for e in expr.elts:
            atom = _resolve_dim_atom(e, mod, env, envs)
            if isinstance(atom, tuple):
                return UNRESOLVED
            parts.append(atom if atom is not None else UNRESOLVED)
        return tuple(parts)
    if isinstance(expr, ast.Starred):
        return UNRESOLVED
    return _resolve_dim_atom(expr, mod, env, envs)


def is_pspec_call(mod: ModuleInfo, call: ast.Call) -> bool:
    ds = dotted(call.func)
    if ds is None:
        return False
    last = ds.split(".")[-1]
    if last == "PartitionSpec":
        return True
    if last == "P":
        imp = mod.from_imports.get("P")
        return bool(imp and imp[1] == "PartitionSpec")
    return False


def _collect_module_sites(mod: ModuleInfo, modules: Dict[str, ModuleInfo],
                          model: ShardModel, env: Dict[str, str],
                          envs: Dict[str, Dict[str, str]]) -> None:
    # map every AST node id to its enclosing FuncInfo for attribution
    owner: Dict[int, Optional[FuncInfo]] = {}

    def assign_owner(root: ast.AST, fi: Optional[FuncInfo]) -> None:
        for child in ast.iter_child_nodes(root):
            sub = mod.funcs_by_node.get(id(child))
            here = sub if sub is not None else fi
            owner[id(child)] = here
            assign_owner(child, here)

    assign_owner(mod.tree, None)

    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) and is_pspec_call(mod, node):
            if any(k.arg is None for k in node.keywords):  # P(*dims) style
                dims: List[Dim] = [UNRESOLVED]
            else:
                dims = [_resolve_dim(a, mod, env, envs) for a in node.args]
            model.pspec_sites.append(
                PSpecSite(mod.rel, node.lineno, dims, owner.get(id(node))))
        elif isinstance(node, ast.Call):
            _collect_mesh_axes(mod, node, model, env, envs)
        else:
            target, value = _assign_parts(node)
            if target is not None:
                name = target.id if isinstance(target, ast.Name) else (
                    dotted(target) or "<rules>")
                rs = _rule_set_literal(mod, value, name)
                if rs is not None:
                    model.rule_sets.append(rs)


def _collect_mesh_axes(mod: ModuleInfo, node: ast.Call, model: ShardModel,
                       env: Dict[str, str],
                       envs: Dict[str, Dict[str, str]]) -> None:
    """Axis names declared at ``Mesh(devs, (axes...))`` construction sites
    extend the vocabulary — planes may carry private axes (``silo_dp``)."""
    ds = dotted(node.func)
    if ds is None or ds.split(".")[-1] != "Mesh":
        return
    axis_expr: Optional[ast.expr] = None
    if len(node.args) >= 2:
        axis_expr = node.args[1]
    for kw in node.keywords:
        if kw.arg == "axis_names":
            axis_expr = kw.value
    if not isinstance(axis_expr, (ast.Tuple, ast.List)):
        return
    for elt in axis_expr.elts:
        atom = _resolve_dim_atom(elt, mod, env, envs)
        if isinstance(atom, str) and atom != UNRESOLVED:
            model.mesh_axes.add(atom)


def _rule_set_literal(mod: ModuleInfo, value: ast.expr,
                      name: str) -> Optional[RuleSetSite]:
    """Recognize ``((pattern, P(...)), ...)`` literals — at least one entry,
    every entry a 2-tuple of a string literal and a PartitionSpec call."""
    if not isinstance(value, (ast.Tuple, ast.List)) or not value.elts:
        return None
    patterns: List[Tuple[str, int]] = []
    for elt in value.elts:
        if not (isinstance(elt, (ast.Tuple, ast.List))
                and len(elt.elts) == 2):
            return None
        pat, spec = elt.elts
        if not (isinstance(pat, ast.Constant) and isinstance(pat.value, str)):
            return None
        if not (isinstance(spec, ast.Call) and is_pspec_call(mod, spec)):
            return None
        patterns.append((pat.value, pat.lineno))
    return RuleSetSite(mod.rel, value.lineno, name, patterns)


def enumerate_rule_sets(paths: Sequence[str],
                        repo_root: str) -> List[RuleSetSite]:
    """Standalone enumeration of in-code rule-set literals under ``paths``
    (used by tests to prove the model sees the shipped defaults)."""
    from ..graftlint.analyzer import collect_files, load_modules

    modules = load_modules(collect_files(paths), repo_root)
    return build_model(modules).rule_sets
