"""graftshard — static sharding, HBM-budget & transfer verification of the
TPU execution plane (ISSUE 8).

Third analyzer suite on the shared :mod:`tools.graftlint.clikit` driver
(findings/pragma/baseline/exit-code contract reused):

- **S001** partition-rule coverage — rule sets must end in an explicit
  catch-all, so no named-pytree leaf is silently replicated by fallback;
- **S002** spec validity — PartitionSpec axes must exist on the mesh,
  never repeat, and (when shapes are known via the model registry) divide
  their dimensions;
- **S003** implicit resharding on hot paths — ``device_put`` inside traced
  code, cross-spec binops that force hidden all-gathers;
- **S004** host transfer of sharded arrays — ``np.asarray``/``device_get``
  /``.item()`` on sharded values inside round loops, host round-trips;
- **S005** static HBM budget — model config × partition rules × optimizer
  state through ``jax.eval_shape``, per-device byte totals against a
  v5e/v5p/CPU HBM table, no hardware required.

Run: ``python -m tools.graftshard [paths...]`` or ``fedml_tpu lint
--shard``; ``--model 7b --mesh 4x4`` adds the HBM budget report;
``--runtime`` traces the real mesh/cheetah factories and diffs declared vs
inferred shardings.
"""

from .analyzer import analyze_paths, analyze_paths_with_model  # noqa: F401
from .findings import SHARD_RULES, Finding  # noqa: F401
