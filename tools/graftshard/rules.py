"""Sharding rules S001–S004 over the extracted :class:`ShardModel`.

S001 partition-rule coverage (rule-set literals with no ``.*`` catch-all —
     unmatched leaves silently take the fallback)
S002 spec validity (axes not in the mesh vocabulary, repeated axes inside
     one PartitionSpec; dimension divisibility lives in :mod:`hbm` where
     shapes are known)
S003 implicit resharding on hot paths (``device_put`` inside traced code;
     binops over operands constrained to different specs in one function)
S004 host transfer of sharded arrays (np.asarray/device_get/.item()/float
     on sharded-placed values inside host-side round loops, and
     device_get→device_put host round-trips)

Traced-function marking is borrowed from graftlint's analyzer (same jit
call graph the G-rules use), so "hot path" means the same thing in both
suites.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ..graftlint.analyzer import (
    Analyzer,
    FuncInfo,
    ModuleInfo,
    _is_jaxish,
    _is_numpy,
    _walk_shallow,
    dotted,
)
from .findings import Finding
from .model import PSpecSite, ShardModel

# host-transfer call names on the HOST side (G001 owns the in-jit variant)
HOST_PULL_NUMPY = {"asarray", "array"}
HOST_CASTS = {"float", "int"}

# call-name prefixes whose result is a sharded device placement — the
# mesh/cheetah planes' placement helpers follow this naming
PLACE_PREFIXES = ("_place", "shard_batch")


def _mk(rule: str, mod: ModuleInfo, line: int, message: str) -> Finding:
    return Finding(rule=rule, path=mod.rel, line=line, col=0,
                   message=message, line_text=mod.line_text(line))


def check_shard(model: ShardModel, modules: Dict[str, ModuleInfo],
                lint: Analyzer) -> List[Finding]:
    by_rel = {m.rel: m for m in modules.values()}
    findings: List[Finding] = []
    findings += _check_rule_coverage(model, by_rel)
    findings += _check_spec_validity(model, by_rel)
    for mod in modules.values():
        for fi in mod.funcs_by_node.values():
            if fi.traced:
                findings += _check_hot_path(mod, fi, model)
            else:
                findings += _check_host_transfers(mod, fi)
            findings += _check_delivery_codec(mod, fi)
    return findings


# ---------------------------------------------------------------------------
# S001 — partition-rule coverage
# ---------------------------------------------------------------------------


def _check_rule_coverage(model: ShardModel, by_rel) -> List[Finding]:
    findings: List[Finding] = []
    for rs in model.rule_sets:
        mod = by_rel.get(rs.rel)
        if mod is None:
            continue
        idx = rs.catch_all_index()
        if idx is None:
            pats = ", ".join(repr(p) for p, _l in rs.patterns)
            findings.append(_mk(
                "S001", mod, rs.line,
                f"partition rule set {rs.name} ({pats}) has no catch-all "
                "— a leaf no pattern matches silently takes the fallback "
                "(match_partition_rules defaults to replicate); add an "
                "explicit '.*' terminal rule so every leaf's placement is "
                "a decision, not an accident"))
        elif idx != len(rs.patterns) - 1:
            # first-match-wins: everything after the catch-all is dead
            dead = [repr(p) for p, _l in rs.patterns[idx + 1:]]
            findings.append(_mk(
                "S001", mod, rs.patterns[idx][1],
                f"partition rule set {rs.name}: catch-all pattern "
                f"{rs.patterns[idx][0]!r} at position {idx} shadows the "
                f"{len(dead)} later rule(s) ({', '.join(dead)}) — "
                "first match wins, so they can never apply; move the "
                "catch-all last"))
    return findings


# ---------------------------------------------------------------------------
# S002 — spec validity
# ---------------------------------------------------------------------------


def _check_spec_validity(model: ShardModel, by_rel) -> List[Finding]:
    findings: List[Finding] = []
    vocab = model.vocabulary
    for site in model.pspec_sites:
        mod = by_rel.get(site.rel)
        if mod is None:
            continue
        axes = site.axes()
        for ax in axes:
            if ax not in vocab:
                findings.append(_mk(
                    "S002", mod, site.line,
                    f"PartitionSpec names axis {ax!r}, which is not a mesh "
                    f"axis (known: {', '.join(sorted(vocab))}) — "
                    "make_shardings raises on this spec the first time a "
                    "leaf matches it"))
        seen: Set[str] = set()
        for ax in axes:
            if ax in seen:
                findings.append(_mk(
                    "S002", mod, site.line,
                    f"PartitionSpec repeats axis {ax!r} — a mesh axis may "
                    "shard at most one dimension of a value; XLA rejects "
                    "the duplicate at lowering time"))
                break
            seen.add(ax)
    return findings


# ---------------------------------------------------------------------------
# S003 — implicit resharding on hot (traced) paths
# ---------------------------------------------------------------------------


def _is_named_call(mod: ModuleInfo, node: ast.Call, name: str) -> bool:
    ds = dotted(node.func)
    if ds is None:
        return False
    parts = ds.split(".")
    if parts[-1] != name:
        return False
    if len(parts) == 1:
        imp = mod.from_imports.get(name)
        return bool(imp and imp[0].startswith("jax"))
    return _is_jaxish(mod, parts[0])


def _check_hot_path(mod: ModuleInfo, fi: FuncInfo,
                    model: ShardModel) -> List[Finding]:
    findings: List[Finding] = []
    # specs constrained onto locals: x = with_sharding_constraint(y, spec)
    constrained: Dict[str, Optional[tuple]] = {}
    specs_by_line: Dict[int, PSpecSite] = {
        s.line: s for s in model.pspec_sites if s.rel == mod.rel}

    def spec_signature(expr: ast.expr) -> Optional[tuple]:
        """P(...) or NamedSharding(mesh, P(...)) -> canonical layout."""
        if isinstance(expr, ast.Call):
            ds = dotted(expr.func)
            last = ds.split(".")[-1] if ds else ""
            if last == "NamedSharding" and len(expr.args) == 2:
                return spec_signature(expr.args[1])
            site = specs_by_line.get(expr.lineno)
            if site is not None:
                return site.signature()
        return None

    for node in _walk_shallow(fi.node):
        if not isinstance(node, ast.Call):
            continue
        if _is_named_call(mod, node, "device_put"):
            findings.append(_mk(
                "S003", mod, node.lineno,
                f"device_put inside traced code ({fi.qualname}) — a "
                "cross-device copy compiled into the hot path; place "
                "inputs before the jit boundary (or use "
                "with_sharding_constraint, which lets XLA fuse the "
                "layout change)"))

    for node in _walk_shallow(fi.node):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)):
            call = node.value
            ds = dotted(call.func)
            if (ds and ds.split(".")[-1] == "with_sharding_constraint"
                    and len(call.args) >= 2):
                constrained[node.targets[0].id] = spec_signature(
                    call.args[1])

    for node in _walk_shallow(fi.node):
        if not isinstance(node, ast.BinOp):
            continue
        left, right = node.left, node.right
        if not (isinstance(left, ast.Name) and isinstance(right, ast.Name)):
            continue
        ls = constrained.get(left.id)
        rs = constrained.get(right.id)
        if ls is not None and rs is not None and ls != rs:
            findings.append(_mk(
                "S003", mod, node.lineno,
                f"binop combines {left.id!r} (constrained to {ls}) with "
                f"{right.id!r} (constrained to {rs}) — XLA inserts a "
                "hidden all-gather/reshard to reconcile the layouts on "
                "every step; constrain both operands to one spec first"))
    return findings


# ---------------------------------------------------------------------------
# S004 — host transfer of sharded arrays
# ---------------------------------------------------------------------------


def _taints_sharded(mod: ModuleInfo, call: ast.Call) -> bool:
    """Calls whose result is a sharded device placement."""
    ds = dotted(call.func)
    if ds is None:
        return False
    last = ds.split(".")[-1]
    if last == "device_put" and len(call.args) >= 2 and (
            _is_named_call(mod, call, "device_put")):
        return True
    return any(last.startswith(p) or last == p for p in PLACE_PREFIXES)


def _contains_device_get(mod: ModuleInfo, expr: ast.expr) -> Optional[int]:
    for node in ast.walk(expr):
        if isinstance(node, ast.Call) and _is_named_call(mod, node,
                                                         "device_get"):
            return node.lineno
    return None


def _check_host_transfers(mod: ModuleInfo, fi: FuncInfo) -> List[Finding]:
    findings: List[Finding] = []
    sharded: Set[str] = set()
    host_pulled: Dict[str, int] = {}  # name -> device_get line

    for node in _walk_shallow(fi.node):
        if not isinstance(node, ast.Assign):
            continue
        value = node.value
        targets: List[str] = []
        for t in node.targets:
            if isinstance(t, ast.Name):
                targets.append(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                targets += [e.id for e in t.elts if isinstance(e, ast.Name)]
        if not targets:
            continue
        if isinstance(value, ast.Call) and _taints_sharded(mod, value):
            sharded.update(targets)
        get_line = _contains_device_get(mod, value)
        if get_line is not None:
            for t in targets:
                host_pulled[t] = get_line

    # (a) device_get -> device_put round-trip: the host hop is pure waste —
    # device_put reshards device-to-device without staging through host
    for node in _walk_shallow(fi.node):
        if not (isinstance(node, ast.Call)
                and _is_named_call(mod, node, "device_put")
                and node.args):
            continue
        arg = node.args[0]
        pulled = _contains_device_get(mod, arg)
        if pulled is None:
            base = arg
            while isinstance(base, ast.Subscript):
                base = base.value
            if isinstance(base, ast.Name) and base.id in host_pulled:
                pulled = host_pulled[base.id]
        if pulled is not None:
            findings.append(_mk(
                "S004", mod, node.lineno,
                "device_put of a device_get result — a host round-trip "
                f"(gather to host at line {pulled}, re-upload here); "
                "device_put accepts device arrays directly and reshards "
                "device-to-device"))

    # (b) host pulls of sharded values inside loops (nested loops reach the
    # same call through every enclosing level — report each site once)
    loops = [n for n in _walk_shallow(fi.node)
             if isinstance(n, (ast.For, ast.While))]
    seen: Set[tuple] = set()
    for loop in loops:
        for node in ast.walk(loop):
            if not isinstance(node, ast.Call):
                continue
            tainted = _transfer_target(mod, node, sharded)
            if tainted is not None and (node.lineno, node.col_offset,
                                        tainted) not in seen:
                seen.add((node.lineno, node.col_offset, tainted))
                findings.append(_mk(
                    "S004", mod, node.lineno,
                    f"host transfer of sharded array {tainted!r} inside a "
                    "round loop — every iteration gathers all shards over "
                    "ICI to one host; keep the value on device and pull "
                    "one reduced scalar after the loop"))
    return findings


def _check_delivery_codec(mod: ModuleInfo, fi: FuncInfo) -> List[Finding]:
    """S004, delivery-plane prong (ROADMAP device-direct wire path): the
    delta plane's ``encode``/``decode`` must not stage frames through host
    memory — ``np.asarray``/``np.array``/``np.frombuffer``/
    ``np.ascontiguousarray`` on a codec input is the host round-trip the
    device-direct wire path removed (jit'd kernels + dlpack emission), and
    ANY ``.tobytes()`` inside a codec stage is a full-frame byte
    materialization (the raw-frame writer takes zero-copy memoryviews —
    a tobytes can only be a regression hiding in a hot path). Scoped to
    modules under the delivery plane (``delivery`` in the module path) so
    the finding inventory is exactly the codec surface."""
    if "delivery" not in mod.name or fi.name not in ("encode", "decode"):
        return []
    params = set(fi.params())
    findings: List[Finding] = []
    seen_lines: Set[int] = set()
    for node in _walk_shallow(fi.node):
        if not isinstance(node, ast.Call):
            continue
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr == "tobytes"
                and not node.args
                and node.lineno not in seen_lines):
            seen_lines.add(node.lineno)
            findings.append(_mk(
                "S004", mod, node.lineno,
                f"`.tobytes()` inside delivery-plane `{fi.qualname}` "
                "materializes a full frame copy on host — the raw-frame "
                "writer takes zero-copy memoryviews/buffer-protocol "
                "objects; pass the array (or a dlpack host view) through "
                "instead (ROADMAP device-direct wire path)"))
            continue
        ds = dotted(node.func)
        parts = ds.split(".") if ds else []
        if not (len(parts) > 1
                and parts[-1] in ("asarray", "array", "frombuffer",
                                  "ascontiguousarray")
                and _is_numpy(mod, parts[0])):
            continue
        arg = node.args[0] if node.args else None
        base = arg
        while isinstance(base, ast.Subscript):
            base = base.value
        if (isinstance(base, ast.Name) and base.id in params
                and node.lineno not in seen_lines):
            seen_lines.add(node.lineno)
            findings.append(_mk(
                "S004", mod, node.lineno,
                f"`{ds}` materializes codec input `{base.id}` on host "
                f"inside delivery-plane `{fi.qualname}` — every frame "
                "rides device→host→encode→wire (and the reverse on "
                "receive); the device-direct wire path jits this stage "
                "and emits frames from the device buffer (ROADMAP)"))
    return findings


def _transfer_target(mod: ModuleInfo, node: ast.Call,
                     sharded: Set[str]) -> Optional[str]:
    """The sharded local this call pulls to host, if any."""

    def first_arg_name() -> Optional[str]:
        if node.args and isinstance(node.args[0], ast.Name):
            return node.args[0].id
        return None

    func = node.func
    if isinstance(func, ast.Attribute):
        if func.attr == "item" and isinstance(func.value, ast.Name):
            return func.value.id if func.value.id in sharded else None
        ds = dotted(func)
        if ds is not None:
            head, last = ds.split(".")[0], ds.split(".")[-1]
            name = first_arg_name()
            if name in sharded and (
                    (last in HOST_PULL_NUMPY and _is_numpy(mod, head))
                    or (last == "device_get" and _is_jaxish(mod, head))):
                return name
    elif isinstance(func, ast.Name):
        name = first_arg_name()
        if name in sharded:
            if func.id in HOST_CASTS:
                return name
            imp = mod.from_imports.get(func.id)
            if func.id == "device_get" and imp and imp[0].startswith("jax"):
                return name
    return None
