"""Runtime-assisted sharding check: trace the REAL factories, diff specs.

The static rules reason about syntax; this closes the loop on the real
artifact, on a CPU-only host. Before jax is first imported the process is
given ``--xla_force_host_platform_device_count=4`` so an honest 4-way mesh
exists to diff against (forced host devices cost nothing).

Three certifications:

1. **Rule coverage is total** — the shipped ``DEFAULT_COHORT_RULES`` /
   ``DEFAULT_STATE_RULES`` resolve every leaf of the canonical cohort/state
   named trees with ``fallback=None`` (a leaf that would need the fallback
   is the S001 failure mode, proven on the real resolver, not a model of
   it).
2. **mesh_api places what the rules say** — a tiny ``MeshFedAvgAPI`` over a
   real 4-way ``clients`` mesh gathers a cohort; every placed array's
   ``sharding.spec`` must equal the rule-resolved spec (declared vs
   *actual* placement).
3. **The cheetah step is sharding-stable** — ``CheetahTrainer``'s train
   step is AOT-lowered on a real fsdp=4 mesh with the declared input
   shardings; the compiled program's *output* shardings must hand back
   params/opt-state in the SAME specs (a mismatch means XLA reshards the
   state every step — S003 at program granularity, the jaxpr-level
   complement of the AST rule).
"""

from __future__ import annotations

import os
import sys
from typing import List

from .findings import Finding

_FORCED_DEVICES = 4


def _ensure_devices() -> None:
    """Force multi-device CPU before jax's first import (no-op after)."""
    if "jax" in sys.modules:
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{_FORCED_DEVICES}").strip()
    # this pass is DEFINED over forced host devices — on a TPU host the
    # ambient JAX_PLATFORMS would otherwise pin jax to 1 real chip and the
    # 4-way mesh could never exist
    os.environ["JAX_PLATFORMS"] = "cpu"


def check_shard_runtime(repo_root: str) -> List[Finding]:
    _ensure_devices()
    sys.path.insert(0, repo_root)
    try:
        import jax  # noqa: F401
    except Exception as e:  # pragma: no cover - env without jax
        raise RuntimeError(
            f"graftshard --runtime unavailable: {type(e).__name__}: {e}"
        ) from e
    findings: List[Finding] = []
    findings += _check_rule_coverage()
    findings += _check_mesh_api_placement()
    findings += _check_cheetah_sharding_stability()
    return findings


def _rt_finding(rule: str, rel: str, message: str, key: str) -> Finding:
    # line_text carries the issue so each distinct runtime failure gets its
    # own baseline key instead of collapsing onto one suppressible entry
    return Finding(rule=rule, path=rel, line=1, col=0,
                   message=f"runtime sharding check: {message}",
                   line_text=f"runtime::{key}")


# ---------------------------------------------------------------------------
# 1. rule coverage on the real resolver
# ---------------------------------------------------------------------------


def _check_rule_coverage() -> List[Finding]:
    import numpy as np

    from fedml_tpu.scale.partition_rules import (
        DEFAULT_COHORT_RULES,
        DEFAULT_STATE_RULES,
        match_partition_rules,
    )

    rel = "fedml_tpu/scale/partition_rules.py"
    findings: List[Finding] = []
    # the canonical named trees mesh_api actually resolves (cohort leaf
    # names are mesh_api literals; state trees keep their pytree paths)
    cohort_tree = {
        "cohort/x": np.zeros((8, 4, 3), np.float32),
        "cohort/y": np.zeros((8, 4), np.int32),
        "cohort/counts": np.zeros((8,), np.int32),
        "cohort/aux": np.zeros((8, 2), np.uint32),
    }
    state_tree = {
        "global_params": {"w": np.zeros((4, 3), np.float32),
                          "b": np.zeros((3,), np.float32)},
        "server_opt_state": {"m": {"w": np.zeros((4, 3), np.float32)}},
    }
    for name, rules, tree in (
        ("DEFAULT_COHORT_RULES", DEFAULT_COHORT_RULES, cohort_tree),
        ("DEFAULT_STATE_RULES", DEFAULT_STATE_RULES, state_tree),
    ):
        try:
            match_partition_rules(rules, tree, fallback=None)
        except ValueError as e:
            findings.append(_rt_finding(
                "S001", rel,
                f"{name} does not cover every canonical leaf: {e}",
                f"coverage::{name}"))
    return findings


# ---------------------------------------------------------------------------
# 2. mesh_api: declared rules vs actual placement
# ---------------------------------------------------------------------------


def _tiny_mesh_api():
    import jax

    import fedml_tpu as fedml
    from fedml_tpu import data as data_mod
    from fedml_tpu import models as model_mod
    from fedml_tpu.arguments import Arguments
    from fedml_tpu.simulation.mesh_api import MeshFedAvgAPI

    # the ambient environment may force any device count (tests force 8);
    # the clients axis spans whatever is actually visible
    n = len(jax.devices())
    args = fedml.init(Arguments(overrides=dict(
        dataset="synthetic", model="lr", client_num_in_total=2 * n,
        client_num_per_round=n, comm_round=1, epochs=1, batch_size=8,
        learning_rate=0.1, backend="mesh", mesh_shape=f"clients:{n}",
    )), should_init_logs=False)
    ds, od = data_mod.load(args)
    return MeshFedAvgAPI(args, fedml.get_device(args), ds,
                         model_mod.create(args, od))


def _check_mesh_api_placement() -> List[Finding]:
    import jax
    import numpy as np

    rel = "fedml_tpu/simulation/mesh_api.py"
    findings: List[Finding] = []
    if len(jax.devices()) < 4:
        return [_rt_finding(
            "S003", rel,
            f"only {len(jax.devices())} device(s) visible — could not "
            "build the 4-way mesh to verify placement (jax imported "
            "before the device-count flag?)", "mesh::devices")]
    api = _tiny_mesh_api()
    from fedml_tpu.scale.partition_rules import match_partition_rules

    cohort = np.arange(len(jax.devices()))
    placed = api._gather_resident(cohort)
    named = {
        "cohort/x": placed[0], "cohort/y": placed[1],
        "cohort/counts": placed[2],
    }
    declared = match_partition_rules(api.cohort_rules, named)
    for name in named:
        actual = named[name].sharding.spec
        want = declared[name]
        if tuple(actual) != tuple(want):
            findings.append(_rt_finding(
                "S003", rel,
                f"cohort leaf {name!r} placed as {tuple(actual)} but the "
                f"rules declare {tuple(want)} — the round program "
                "reshards it on entry every round",
                f"mesh::{name}"))
    return findings


# ---------------------------------------------------------------------------
# 3. cheetah: the step must preserve its declared shardings
# ---------------------------------------------------------------------------


def _check_cheetah_sharding_stability() -> List[Finding]:
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from fedml_tpu.parallel.context import mesh_context
    from fedml_tpu.parallel.pipeline import _opt_state_specs
    from fedml_tpu.parallel.sharding import make_mesh
    from fedml_tpu.parallel.train_step import CheetahTrainer, TrainState
    from fedml_tpu.parallel.transformer import TransformerConfig

    rel = "fedml_tpu/parallel/train_step.py"
    findings: List[Finding] = []
    if len(jax.devices()) < 4:
        return []  # already reported by the mesh_api check
    mesh = make_mesh({"fsdp": 4}, devices=jax.devices()[:4])
    trainer = CheetahTrainer(TransformerConfig.tiny(), mesh)
    params_abs = jax.eval_shape(
        trainer._init_raw, jax.random.PRNGKey(0))["params"]
    opt_abs = jax.eval_shape(trainer.opt.init, params_abs)
    p_spec = jax.tree.map(
        lambda s: s.spec, trainer.param_shardings,
        is_leaf=lambda x: isinstance(x, NamedSharding))
    o_spec = _opt_state_specs(p_spec, opt_abs)

    def sds(al, spec):
        return jax.ShapeDtypeStruct(
            al.shape, al.dtype, sharding=NamedSharding(mesh, spec))

    state_abs = TrainState(
        step=sds(jax.ShapeDtypeStruct((), jnp.int32), P()),
        params=jax.tree.map(sds, params_abs, p_spec),
        opt_state=jax.tree.map(
            sds, opt_abs, o_spec,
            is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct)),
    )
    tok = jax.ShapeDtypeStruct((4, 16), jnp.int32,
                               sharding=trainer._batch_shard)
    with mesh, mesh_context(mesh):
        compiled = trainer._step_jit.lower(state_abs, tok, tok).compile()
    out_state = compiled.output_shardings[0]

    extents = {name: int(mesh.shape[name]) for name in mesh.axis_names}
    for label, spec_tree, out_tree in (
        ("param", p_spec, out_state.params),
        ("opt-state", o_spec, out_state.opt_state),
    ):
        declared = dict(_spec_items(spec_tree))
        for path, sharding in _sharding_items(out_tree):
            want = declared.get(path)
            got = getattr(sharding, "spec", None)
            if want is not None and got is not None and (
                    _normalize(got, extents) != _normalize(want, extents)):
                leaf = "/".join(map(str, path))
                findings.append(_rt_finding(
                    "S003", rel,
                    f"train step returns {label} {leaf!r} as "
                    f"{tuple(got)} but its declared sharding is "
                    f"{tuple(want)} — every step pays a reshard to "
                    "restore the layout", f"cheetah::{label}::{leaf}"))
    return findings


def _spec_items(tree):
    from jax.sharding import PartitionSpec as P
    from jax.tree_util import tree_flatten_with_path

    flat, _ = tree_flatten_with_path(
        tree, is_leaf=lambda x: isinstance(x, P))
    return [(_plain_path(path), spec) for path, spec in flat]


def _sharding_items(tree):
    from jax.tree_util import tree_flatten_with_path

    flat, _ = tree_flatten_with_path(tree)
    return [(_plain_path(path), leaf) for path, leaf in flat]


def _plain_path(path) -> tuple:
    from .hbm import _key_str

    return tuple(_key_str(k) for k in path)


def _normalize(spec, extents) -> tuple:
    """Canonical layout modulo no-op annotations: axes of extent 1 shard
    nothing (XLA reports ('tensor','fsdp') as (None,'fsdp') when tensor=1),
    and trailing Nones are implicit (P('fsdp') == P('fsdp', None))."""
    dims = []
    for dim in tuple(spec):
        axes = tuple(
            ax for ax in (dim if isinstance(dim, tuple) else (dim,))
            if ax is not None and extents.get(ax, 1) > 1)
        dims.append(axes if len(axes) > 1 else (axes[0] if axes else None))
    while dims and dims[-1] is None:
        dims.pop()
    return tuple(dims)
