"""graftlint CLI: ``python -m tools.graftlint [paths...]``.

Exit codes: 0 clean (after baseline + pragmas), 1 findings, 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from . import baseline as baseline_mod
from .analyzer import analyze_paths
from .findings import RULES, Finding


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="graftlint",
        description="JAX-aware static analysis: trace-safety, donation, "
                    "recompile and thread-safety linting",
    )
    p.add_argument("paths", nargs="*", default=["fedml_tpu"],
                   help="files or directories to analyze (default: fedml_tpu)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--baseline", default="",
                   help="baseline file (default: <repo-root>/tools/graftlint/"
                        "baseline.json, resolved independent of cwd)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, ignoring the baseline")
    p.add_argument("--write-baseline", action="store_true",
                   help="rewrite the baseline from the current findings")
    p.add_argument("--select", default="",
                   help="comma-separated rule ids to report (e.g. G001,G005)")
    p.add_argument("--runtime", action="store_true",
                   help="also trace the round engine under jax.make_jaxpr "
                        "and check the jaxprs for effects (imports jax)")
    p.add_argument("--list-rules", action="store_true")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for rid, (title, hint) in RULES.items():
            print(f"{rid}  {title}\n      fix: {hint}")
        return 0

    for p in args.paths:
        if not os.path.exists(p):
            print(f"graftlint: no such path: {p}", file=sys.stderr)
            return 2

    repo_root = baseline_mod.find_repo_root(args.paths[0])
    findings = analyze_paths(args.paths, repo_root=repo_root)

    if args.runtime:
        from .runtime_check import check_round_engine

        try:
            findings = findings + check_round_engine(repo_root)
        except RuntimeError as e:
            print(f"graftlint: {e}", file=sys.stderr)
            return 2

    if args.select:
        keep = {r.strip().upper() for r in args.select.split(",") if r.strip()}
        findings = [f for f in findings if f.rule in keep]

    baseline_path = args.baseline or baseline_mod.default_baseline_path(
        repo_root)
    if args.write_baseline:
        if args.select:
            print("graftlint: --write-baseline with --select would drop "
                  "every other rule's entries from the baseline — refusing",
                  file=sys.stderr)
            return 2
        baseline_mod.save(baseline_path, findings)
        print(f"graftlint: wrote {len(findings)} finding(s) to "
              f"{os.path.relpath(baseline_path, repo_root)}")
        return 0

    if args.no_baseline:
        new, baselined = findings, []
    else:
        new, baselined = baseline_mod.split(
            findings, baseline_mod.load(baseline_path))

    if args.format == "json":
        print(json.dumps({
            "findings": [f.to_json() for f in new],
            "baselined": len(baselined),
            "counts": _counts(new),
            "exit_code": 1 if new else 0,
        }, indent=2))
    else:
        for f in new:
            print(f.render())
            if f.hint:
                print(f"    fix: {f.hint}")
        summary = (f"graftlint: {len(new)} finding(s)"
                   f" ({len(baselined)} baselined)")
        print(summary if new or baselined else "graftlint: clean")
    return 1 if new else 0


def _counts(findings: List[Finding]) -> dict:
    out: dict = {}
    for f in findings:
        out[f.rule] = out.get(f.rule, 0) + 1
    return out


if __name__ == "__main__":
    raise SystemExit(main())
