"""graftlint CLI: ``python -m tools.graftlint [paths...]``.

Thin suite definition over the shared driver (:mod:`tools.graftlint.clikit`
— flags, baseline handling, rendering, and the exit-code contract live
there, shared with graftproto). Exit codes: 0 clean (after baseline +
pragmas), 1 findings, 2 usage error OR analyzer crash — CI can tell "the
tree regressed" (1) from "the linter itself broke" (2) at a glance; that
includes crashes inside the ``--runtime`` jaxpr pass.
"""

from __future__ import annotations

import argparse
from typing import Dict, List, Optional, Tuple

from . import clikit
from .analyzer import analyze_paths
from .baseline import DEFAULT_BASELINE_RELPATH
from .findings import RULES, Finding


def _add_arguments(p: argparse.ArgumentParser) -> None:
    p.add_argument("--runtime", action="store_true",
                   help="also trace the round engine under jax.make_jaxpr "
                        "and check the jaxprs for effects (imports jax)")


def _analyze(args: argparse.Namespace,
             repo_root: str) -> Tuple[List[Finding], Dict]:
    findings = analyze_paths(args.paths, repo_root=repo_root)
    if args.runtime:
        from .runtime_check import check_round_engine

        try:
            findings = findings + check_round_engine(repo_root)
        except RuntimeError as e:
            # an operator-fixable condition (e.g. jax missing): one line,
            # exit 2, no traceback; anything else crashes through to the
            # driver's internal-error handler (also exit 2)
            raise clikit.SuiteUsageError(str(e)) from e
    return findings, {}


def main(argv: Optional[List[str]] = None) -> int:
    return clikit.run_suite(
        argv,
        tool="graftlint",
        description="JAX-aware static analysis: trace-safety, donation, "
                    "recompile and thread-safety linting",
        rules=RULES,
        analyze=_analyze,
        baseline_relpath=DEFAULT_BASELINE_RELPATH,
        add_arguments=_add_arguments,
    )


if __name__ == "__main__":
    raise SystemExit(main())
