"""Shared CLI driver for the lint suites (graftlint / graftproto /
graftshard / graftrep).

One implementation of the common contract so the suites cannot drift:

- flags: paths, --format text|json (--json alias), --baseline,
  --no-baseline, --write-baseline (refused with --select), --select,
  --list-rules, plus suite-specific extras via ``add_arguments``;
- exit codes: 0 clean (after baseline + pragmas), 1 findings, 2 usage
  error OR the analyzer itself crashed — CI can tell "the tree regressed"
  (1) from "the linter broke" (2) at a glance. ANY exception escaping the
  suite's ``analyze`` maps to 2 (with traceback); a
  :class:`SuiteUsageError` maps to 2 with a one-line message instead.
- JSON payload: ``findings`` / ``baselined`` / ``counts`` / ``exit_code``
  plus whatever extra fields the suite's ``analyze`` returns.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Callable, Dict, List, Optional, Tuple

from . import baseline as baseline_mod
from .findings import Finding


class SuiteUsageError(RuntimeError):
    """An analysis-time condition the operator must fix (bad flag combo,
    missing optional dependency): reported as one line, exit 2, no
    traceback."""


AnalyzeFn = Callable[[argparse.Namespace, str], Tuple[List[Finding], Dict]]


def run_suite(
    argv: Optional[List[str]],
    *,
    tool: str,
    description: str,
    rules: Dict[str, Tuple[str, str]],
    analyze: AnalyzeFn,
    baseline_relpath: str,
    add_arguments: Optional[Callable[[argparse.ArgumentParser], None]] = None,
) -> int:
    p = argparse.ArgumentParser(prog=tool, description=description)
    p.add_argument("paths", nargs="*", default=["fedml_tpu"],
                   help="files or directories to analyze (default: fedml_tpu)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--json", action="store_true",
                   help="shorthand for --format json")
    p.add_argument("--baseline", default="",
                   help=f"baseline file (default: <repo-root>/"
                        f"{baseline_relpath.replace(os.sep, '/')}, resolved "
                        "independent of cwd)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, ignoring the baseline")
    p.add_argument("--write-baseline", action="store_true",
                   help="rewrite the baseline from the current findings")
    p.add_argument("--select", default="",
                   help="comma-separated rule ids to report")
    p.add_argument("--list-rules", action="store_true")
    if add_arguments is not None:
        add_arguments(p)
    args = p.parse_args(argv)
    if args.json:
        args.format = "json"

    if args.list_rules:
        for rid, (title, hint) in rules.items():
            print(f"{rid}  {title}\n      fix: {hint}")
        return 0

    for path in args.paths:
        if not os.path.exists(path):
            print(f"{tool}: no such path: {path}", file=sys.stderr)
            return 2

    repo_root = baseline_mod.find_repo_root(args.paths[0])
    try:
        findings, extra = analyze(args, repo_root)
    except SuiteUsageError as e:
        print(f"{tool}: {e}", file=sys.stderr)
        return 2
    except Exception:  # noqa: BLE001 — a crashed analyzer is exit 2, not 1
        import traceback

        traceback.print_exc()
        print(f"{tool}: internal error while analyzing (this is a bug in "
              "the analyzer, not a finding)", file=sys.stderr)
        return 2

    if args.select:
        keep = {r.strip().upper() for r in args.select.split(",") if r.strip()}
        findings = [f for f in findings if f.rule in keep]

    baseline_path = args.baseline or os.path.join(repo_root, baseline_relpath)
    if args.write_baseline:
        if args.select:
            print(f"{tool}: --write-baseline with --select would drop "
                  "every other rule's entries from the baseline — refusing",
                  file=sys.stderr)
            return 2
        baseline_mod.save(baseline_path, findings, tool=tool)
        print(f"{tool}: wrote {len(findings)} finding(s) to "
              f"{os.path.relpath(baseline_path, repo_root)}")
        return 0

    if args.no_baseline:
        new, baselined = findings, []
    else:
        new, baselined = baseline_mod.split(
            findings, baseline_mod.load(baseline_path))

    if args.format == "json":
        print(json.dumps({
            "findings": [f.to_json() for f in new],
            "baselined": len(baselined),
            "counts": _counts(new),
            **extra,
            "exit_code": 1 if new else 0,
        }, indent=2))
    else:
        for f in new:
            print(f.render())
            if f.hint:
                print(f"    fix: {f.hint}")
        summary = (f"{tool}: {len(new)} finding(s)"
                   f" ({len(baselined)} baselined)")
        print(summary if new or baselined else f"{tool}: clean")
    return 1 if new else 0


def _counts(findings: List[Finding]) -> dict:
    out: dict = {}
    for f in findings:
        out[f.rule] = out.get(f.rule, 0) + 1
    return out
