"""Finding record + rule registry (ids, one-line docs, autofix hints).

The registry is shared infrastructure: sibling suites (tools/graftproto's
P-rules) register their ids via :func:`register_rules` so one
:class:`Finding` type renders/bases/JSONs identically across suites.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

# rule id -> (title, autofix hint)
RULES: Dict[str, Tuple[str, str]] = {
    "G001": (
        "host-sync-in-jit",
        "keep the value on device (jnp ops / _masked_mean-style kernels); "
        "realize host floats only outside the traced region, or mark the "
        "argument static and pragma the line if it is trace-time config",
    ),
    "G002": (
        "donation-reuse",
        "adopt the returned state and never read the donated argument again; "
        "rebind the name from the call's result or copy-to-host first",
    ),
    "G003": (
        "recompile-hazard",
        "pass data-derived scalars via static_argnums (or hoist them out of "
        "the call); build pytrees from deterministically ordered containers, "
        "never from set iteration",
    ),
    "G004": (
        "impure-round-fn",
        "return new state instead of mutating captured objects; move "
        "telemetry/logging to the host-side wrapper around the dispatch",
    ),
    "G005": (
        "unguarded-shared-state",
        "guard the attribute with a threading.Lock, replace boolean flags "
        "with threading.Event, or document the happens-before edge and "
        "pragma the line",
    ),
}


def register_rules(rules: Dict[str, Tuple[str, str]]) -> None:
    """Merge a sibling suite's rule registry (id -> (title, hint)) so its
    findings render with titles/hints. Re-registering the same id with the
    same payload is a no-op; a conflicting payload is a programming error."""
    for rid, payload in rules.items():
        existing = RULES.get(rid)
        if existing is not None and existing != payload:
            raise ValueError(f"rule id {rid!r} already registered "
                             f"with a different title/hint")
        RULES[rid] = payload


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-root-relative posix path
    line: int
    col: int
    message: str
    line_text: str = ""  # stripped source line, used for baseline matching

    @property
    def title(self) -> str:
        return RULES.get(self.rule, ("?", ""))[0]

    @property
    def hint(self) -> str:
        return RULES.get(self.rule, ("?", ""))[1]

    def baseline_key(self) -> str:
        # line-number-free so unrelated edits above don't churn the baseline
        return f"{self.path}::{self.rule}::{self.line_text}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule} [{self.title}] {self.message}")

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "title": self.title,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "hint": self.hint,
        }
