"""Checked-in baseline: pre-existing findings, suppressed but visible.

Keys are ``<repo-relative path>::<rule>::<stripped source line>`` with an
occurrence count — line-number-free so edits elsewhere in a file don't churn
the baseline, repo-root-anchored so results are identical from any cwd.
"""

from __future__ import annotations

import json
import os
from collections import Counter
from typing import Iterable, List, Tuple

from .findings import Finding

DEFAULT_BASELINE_RELPATH = os.path.join("tools", "graftlint", "baseline.json")

_ROOT_MARKERS = ("pyproject.toml", ".git")


def find_repo_root(start: str) -> str:
    """Walk up from ``start`` to the first dir holding a root marker."""
    cur = os.path.abspath(start)
    if os.path.isfile(cur):
        cur = os.path.dirname(cur)
    while True:
        if any(os.path.exists(os.path.join(cur, m)) for m in _ROOT_MARKERS):
            return cur
        parent = os.path.dirname(cur)
        if parent == cur:
            return os.path.abspath(start if os.path.isdir(start)
                                   else os.path.dirname(start))
        cur = parent


def default_baseline_path(repo_root: str) -> str:
    return os.path.join(repo_root, DEFAULT_BASELINE_RELPATH)


def load(path: str) -> Counter:
    if not path or not os.path.exists(path):
        return Counter()
    with open(path) as f:
        data = json.load(f)
    return Counter({str(k): int(v) for k, v in data.get("findings", {}).items()})


def save(path: str, findings: Iterable[Finding],
         tool: str = "graftlint") -> None:
    counts = Counter(f.baseline_key() for f in findings)
    payload = {
        "version": 1,
        "comment": (
            f"{tool} baseline: pre-existing findings, suppressed but "
            "visible. Regenerate with --write-baseline; shrink it, never "
            "grow it."
        ),
        "findings": {k: counts[k] for k in sorted(counts)},
    }
    with open(path, "w") as f:
        json.dump(payload, f, indent=2, sort_keys=False)
        f.write("\n")


def split(findings: List[Finding], baseline: Counter
          ) -> Tuple[List[Finding], List[Finding]]:
    """(new, baselined) — up to the baselined count per key is suppressed,
    matched in line order."""
    budget = Counter(baseline)
    new: List[Finding] = []
    old: List[Finding] = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule)):
        k = f.baseline_key()
        if budget.get(k, 0) > 0:
            budget[k] -= 1
            old.append(f)
        else:
            new.append(f)
    return new, old
