"""G005 unguarded-shared-state: cross-thread attribute races.

Two sub-rules, both purely syntactic:

1. **Instance attributes**: within a class, partition methods into a
   *thread side* (methods used as ``threading.Thread(target=...)`` anywhere
   in the analyzed tree, callback-assigned methods/closures, ``run`` of a
   Thread subclass, plus their intra-class call closure) and a *main side*
   (everything else; ``__init__``'s own body counts as pre-thread setup).
   An attribute written unguarded on one side and accessed unguarded on the
   other — and not itself a Lock/Event/Queue — is flagged.

2. **Module-level namespaces** (the ``_State.x`` pattern): in modules that
   construct threads, an unguarded read-then-write of the same class
   attribute inside one function is a check-then-act / read-modify-write
   race.

"Guarded" = lexically inside ``with self.<lock>:`` (or any ``with`` whose
context expression names a lock). A method whose every intra-class call site
is guarded inherits the guard.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .analyzer import dotted as _dotted
from .findings import Finding

LOCK_TYPES = {"Lock", "RLock", "Condition"}
SAFE_TYPES = LOCK_TYPES | {
    "Event", "Semaphore", "BoundedSemaphore", "Barrier", "Queue",
    "LifoQueue", "PriorityQueue", "SimpleQueue", "deque", "local",
}
CONCURRENCY_CTORS = {"Thread", "ThreadPoolExecutor", "server", "Client",
                     "Timer", "Process"}
WRITE_METHODS = {
    "append", "extend", "insert", "pop", "popitem", "clear", "update",
    "setdefault", "remove", "discard", "add", "put",
}


def _is_lock_expr(ds: Optional[str], lock_attrs: Set[str]) -> bool:
    if not ds:
        return False
    last = ds.split(".")[-1]
    return last in lock_attrs or "lock" in last.lower()


class _Access:
    __slots__ = ("attr", "write", "guarded", "line", "owner")

    def __init__(self, attr: str, write: bool, guarded: bool, line: int,
                 owner: str):
        self.attr = attr
        self.write = write
        self.guarded = guarded
        self.line = line
        self.owner = owner


def _mk(mod, node_line: int, message: str) -> Finding:
    return Finding(rule="G005", path=mod.rel, line=node_line, col=0,
                   message=message, line_text=mod.line_text(node_line))


# ---------------------------------------------------------------------------


def check_module_threads(mod, thread_entry_names: Set[str]) -> List[Finding]:
    findings: List[Finding] = []
    module_creates_thread = _module_creates(mod.tree, {"Thread", "Timer"})
    for cls_name, methods in mod.classes.items():
        if not methods:
            continue
        findings += _check_class(mod, cls_name, methods, thread_entry_names)
    if module_creates_thread:
        findings += _check_module_state_rmw(mod)
    return findings


def _module_creates(tree: ast.AST, ctors: Set[str]) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            ds = _dotted(node.func)
            if ds and ds.split(".")[-1] in ctors:
                return True
    return False


# ---------------------------------------------------------------------------
# Sub-rule 1: instance attributes
# ---------------------------------------------------------------------------


def _check_class(mod, cls_name: str, methods: Dict[str, object],
                 thread_entry_names: Set[str]) -> List[Finding]:
    class_node = _find_class_node(mod.tree, cls_name)
    if class_node is None:
        return []

    lock_attrs: Set[str] = set()
    safe_attrs: Set[str] = set()
    concurrent = any("Thread" in b for b in mod.class_bases.get(cls_name, []))
    # class-body assignments (``_lock = threading.Lock()``)
    for stmt in class_node.body:
        if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
            ds = _dotted(stmt.value.func)
            last = ds.split(".")[-1] if ds else ""
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    if last in LOCK_TYPES:
                        lock_attrs.add(t.id)
                    if last in SAFE_TYPES:
                        safe_attrs.add(t.id)

    for m in methods.values():
        for node in ast.walk(m.node):
            if isinstance(node, ast.Call):
                ds = _dotted(node.func)
                last = ds.split(".")[-1] if ds else ""
                if last in CONCURRENCY_CTORS:
                    concurrent = True
            targets: List[ast.expr] = []
            value = None
            if (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                targets, value = node.targets, node.value
            elif (isinstance(node, ast.AnnAssign)
                  and isinstance(node.value, ast.Call)):
                targets, value = [node.target], node.value
            if value is not None:
                ds = _dotted(value.func)
                last = ds.split(".")[-1] if ds else ""
                for t in targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        if last in LOCK_TYPES:
                            lock_attrs.add(t.attr)
                        if last in SAFE_TYPES:
                            safe_attrs.add(t.attr)

    # entry methods + callback-assigned members / closures
    entries: Set[str] = set()
    callback_closures: Set[int] = set()  # id() of nested FunctionDef nodes
    for name, m in methods.items():
        if name in thread_entry_names:
            entries.add(name)
        if concurrent and name == "run":
            entries.add(name)
        nested_defs = {n.name: n for n in ast.walk(m.node)
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))
                       and n is not m.node}
        for node in ast.walk(m.node):
            if isinstance(node, ast.Assign):
                v = node.targets[0] if node.targets else None
                val_ds = _dotted(node.value)
                if isinstance(v, ast.Attribute):
                    # self._client.on_connect = self.m / local closure
                    if val_ds and val_ds.startswith("self."):
                        mm = val_ds.split(".", 1)[1]
                        if mm in methods and (
                                v.attr.startswith("on_")
                                or not isinstance(v.value, ast.Name)
                                or v.value.id != "self"):
                            entries.add(mm)
                    elif (isinstance(node.value, ast.Name)
                          and node.value.id in nested_defs):
                        callback_closures.add(id(nested_defs[node.value.id]))
            elif isinstance(node, ast.keyword) and node.arg == "target":
                val_ds = _dotted(node.value)
                if val_ds and val_ds.startswith("self."):
                    mm = val_ds.split(".", 1)[1]
                    if mm in methods:
                        entries.add(mm)
                elif (isinstance(node.value, ast.Name)
                      and node.value.id in nested_defs):
                    callback_closures.add(id(nested_defs[node.value.id]))
            elif isinstance(node, ast.Call) and concurrent:
                # a closure escaping into a handler registry / callback slot
                for a in list(node.args) + [k.value for k in node.keywords
                                            if k.arg != "target"]:
                    if (isinstance(a, ast.Name) and a.id in nested_defs):
                        callback_closures.add(id(nested_defs[a.id]))

    if not entries and not callback_closures:
        return []

    # thread-side closure over intra-class self.m() calls
    thread_side: Set[str] = set(entries)
    changed = True
    while changed:
        changed = False
        for name in list(thread_side):
            m = methods.get(name)
            if m is None:
                continue
            for node in ast.walk(m.node):
                if isinstance(node, ast.Call):
                    ds = _dotted(node.func)
                    if ds and ds.startswith("self."):
                        callee = ds.split(".")[1]
                        if callee in methods and callee not in thread_side:
                            thread_side.add(callee)
                            changed = True

    # methods reachable only from __init__ run before any thread exists
    callers: Dict[str, Set[str]] = {}
    for name, m in methods.items():
        for node in ast.walk(m.node):
            if isinstance(node, ast.Call):
                ds = _dotted(node.func)
                if ds and ds.startswith("self."):
                    parts = ds.split(".")
                    if len(parts) == 2 and parts[1] in methods:
                        callers.setdefault(parts[1], set()).add(name)
    setup_methods = {"__init__"}
    for name in methods:
        who = callers.get(name, set())
        if who and who <= setup_methods and name not in thread_side:
            setup_methods.add(name)

    # collect accesses
    accesses: List[_Access] = []
    guarded_calls: Dict[str, List[bool]] = {}
    for name, m in methods.items():
        side_thread = name in thread_side
        setup = name in setup_methods
        _collect_accesses(
            m.node, owner=name, thread=side_thread, setup=setup,
            lock_attrs=lock_attrs, callback_closures=callback_closures,
            accesses=accesses, guarded_calls=guarded_calls, methods=methods,
        )

    # guard inheritance: every in-class call site guarded → method guarded
    fully_guarded = {name for name, flags in guarded_calls.items()
                     if flags and all(flags)}
    for a in accesses:
        if a.owner in fully_guarded:
            a.guarded = True

    findings: List[Finding] = []
    by_attr: Dict[str, List[_Access]] = {}
    for a in accesses:
        if a.attr not in safe_attrs and a.attr not in lock_attrs:
            by_attr.setdefault(a.attr, []).append(a)
    for attr, accs in sorted(by_attr.items()):
        main = [a for a in accs if a.owner not in thread_side
                and not a.owner.startswith("<closure")]
        thr = [a for a in accs if a.owner in thread_side
               or a.owner.startswith("<closure")]
        main_w = [a for a in main if a.write and not a.guarded]
        thr_w = [a for a in thr if a.write and not a.guarded]
        main_any = [a for a in main if not a.guarded]
        thr_any = [a for a in thr if not a.guarded]
        hit = None
        if main_w and thr_any:
            hit = (main_w[0], thr_any[0])
        elif thr_w and main_any:
            hit = (main_any[0], thr_w[0])
        if hit is not None:
            a_main, a_thr = hit
            findings.append(_mk(
                mod, (a_main.line if a_main.write else a_thr.line),
                f"`self.{attr}` in `{cls_name}` is accessed from both "
                f"main-thread code (`{a_main.owner}`, line {a_main.line}) "
                f"and thread-side code (`{a_thr.owner}`, line {a_thr.line}) "
                "with at least one unguarded write — guard it with a lock "
                "or use threading.Event/queue.Queue",
            ))
    return findings


def _find_class_node(tree: ast.AST, name: str) -> Optional[ast.ClassDef]:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == name:
            return node
    return None


def _collect_accesses(func_node, owner: str, thread: bool, setup: bool,
                      lock_attrs: Set[str], callback_closures: Set[int],
                      accesses: List[_Access],
                      guarded_calls: Dict[str, List[bool]],
                      methods: Dict[str, object]) -> None:
    def walk(node: ast.AST, guarded: bool, cur_owner: str,
             cur_setup: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if id(child) in callback_closures:
                    # callback closure: runs later, on another thread
                    walk(child, guarded,
                         f"<closure {cur_owner}.{child.name}>", False)
                else:
                    walk(child, guarded, cur_owner, cur_setup)
                continue
            if isinstance(child, (ast.With, ast.AsyncWith)):
                _walk_with(child, guarded, cur_owner, cur_setup)
                continue
            if isinstance(child, ast.Call):
                ds = _dotted(child.func)
                if ds and ds.startswith("self."):
                    parts = ds.split(".")
                    if len(parts) == 2 and parts[1] in methods:
                        guarded_calls.setdefault(parts[1], []).append(guarded)
                    elif (len(parts) == 3
                          and parts[-1] in WRITE_METHODS):
                        _record(parts[1], True, guarded, child.lineno,
                                cur_owner, cur_setup)
                walk(child, guarded, cur_owner, cur_setup)
                continue
            if isinstance(child, ast.Assign):
                walk(child.value, guarded, cur_owner, cur_setup)
                for t in child.targets:
                    _target_access(t, guarded, cur_owner, cur_setup)
                    walk(t, guarded, cur_owner, cur_setup)
                continue
            if isinstance(child, ast.AugAssign):
                walk(child.value, guarded, cur_owner, cur_setup)
                _target_access(child.target, guarded, cur_owner, cur_setup)
                continue
            if (isinstance(child, ast.Attribute)
                    and isinstance(child.value, ast.Name)
                    and child.value.id == "self"
                    and isinstance(child.ctx, ast.Load)):
                _record(child.attr, False, guarded, child.lineno, cur_owner,
                        cur_setup)
                continue
            walk(child, guarded, cur_owner, cur_setup)

    def _walk_with(w, guarded: bool, cur_owner: str,
                   cur_setup: bool) -> None:
        # dispatch on the With node ITSELF: body statements that are
        # themselves With nodes must keep accumulating guards — walking
        # their children directly would skip this branch and lose a
        # ``with self._lock:`` nested inside another context manager
        g = guarded or any(
            _is_lock_expr(_dotted(i.context_expr), lock_attrs)
            for i in w.items
        )
        for i in w.items:
            walk(i.context_expr, guarded, cur_owner, cur_setup)
        for stmt in w.body:
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                _walk_with(stmt, g, cur_owner, cur_setup)
            else:
                walk(stmt, g, cur_owner, cur_setup)

    def _target_access(t: ast.expr, guarded: bool, cur_owner: str,
                       cur_setup: bool) -> None:
        # self.X = ... / self.X[...] = ...
        base = t
        if isinstance(base, ast.Subscript):
            base = base.value
        if (isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self"):
            _record(base.attr, True, guarded, t.lineno, cur_owner, cur_setup)

    def _record(attr: str, write: bool, guarded: bool, line: int,
                cur_owner: str, cur_setup: bool) -> None:
        if cur_setup:
            return  # __init__ body runs before any thread exists
        accesses.append(_Access(attr, write, guarded, line, cur_owner))

    walk(func_node, guarded=False, cur_owner=owner, cur_setup=setup)


# ---------------------------------------------------------------------------
# Sub-rule 2: module-level namespace read-modify-write
# ---------------------------------------------------------------------------


def _check_module_state_rmw(mod) -> List[Finding]:
    findings: List[Finding] = []
    class_names = set(mod.classes)
    for fi in mod.funcs_by_node.values():
        reads: Dict[Tuple[str, str], int] = {}
        writes: Dict[Tuple[str, str], Tuple[int, bool]] = {}
        guarded_stack: List[bool] = [False]

        def walk(node: ast.AST) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fi.node:
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                g = guarded_stack[-1] or any(
                    _is_lock_expr(_dotted(i.context_expr), set())
                    for i in node.items
                )
                for i in node.items:
                    walk(i.context_expr)
                guarded_stack.append(g)
                for stmt in node.body:
                    walk(stmt)
                guarded_stack.pop()
                return
            if isinstance(node, ast.Assign):
                walk(node.value)
                for t in node.targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id in class_names):
                        key = (t.value.id, t.attr)
                        if key not in writes:
                            writes[key] = (t.lineno, guarded_stack[-1])
                    else:
                        walk(t)
                return
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id in class_names
                    and isinstance(node.ctx, ast.Load)):
                key = (node.value.id, node.attr)
                if key not in reads:
                    reads[key] = node.lineno
                return
            for child in ast.iter_child_nodes(node):
                walk(child)

        for stmt in (fi.node.body if not isinstance(fi.node, ast.Lambda)
                     else [fi.node.body]):
            walk(stmt)
        for key, (wline, wguard) in sorted(writes.items()):
            rline = reads.get(key)
            if rline is not None and rline < wline and not wguard:
                cls, attr = key
                findings.append(_mk(
                    mod, wline,
                    f"unguarded read-modify-write of module state "
                    f"`{cls}.{attr}` in `{fi.qualname}` (read line {rline}, "
                    f"write line {wline}) — racy when rounds run on a comm "
                    "thread; hold a module lock around the check-and-set",
                ))
    return findings
