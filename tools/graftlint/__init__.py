"""graftlint — JAX-aware static analysis for the fedml_tpu codebase.

The fused round engine (one donated XLA program per round) is a correctness
property that dynamic tests only sample: every new algorithm or defense can
silently reintroduce host syncs, recompiles, donation bugs or cross-thread
races that the parity tests never exercise. graftlint checks the property
statically over the whole tree, wired into CI as a tier-1 gate.

Rules (see docs/graftlint.md):

- **G001 host-sync-in-jit** — ``.item()``/``.tolist()``/``float()``/``int()``
  /``bool()``/``np.asarray``/``print``/``jax.device_get`` on traced values,
  reachable from any ``jax.jit``/``lax.scan``-traced function (call graph
  seeded from ``round_engine.build_round_core``, the sp/mesh cohort programs
  and the cheetah trainer).
- **G002 donation-reuse** — a variable passed to a ``donate_argnums`` call
  site and read again afterwards (use-after-donate).
- **G003 recompile-hazard** — data-derived Python scalars/shapes fed to a jit
  boundary without ``static_argnums``; set-iteration feeding pytree
  construction (nondeterministic structure ⇒ recompile).
- **G004 impure-round-fn** — side effects inside traced functions: attribute
  /container writes on captured state, ``global`` writes, telemetry/logging
  calls that aren't the no-op span.
- **G005 unguarded-shared-state** — attributes mutated from both a thread
  target (or callback) and main-thread code without a lock, plus unguarded
  read-modify-write of module-level state in threaded modules.

Run as ``python -m tools.graftlint fedml_tpu/`` (or ``fedml_tpu lint``).
Suppress a single line with ``# graftlint: disable=G00X``; pre-existing
findings live in the checked-in, repo-root-anchored
``tools/graftlint/baseline.json``.
"""

from .findings import Finding, RULES  # noqa: F401

__version__ = "0.1.0"
