"""Inline suppression: ``# graftlint: disable=G001[,G005]`` or ``=all``.

The pragma suppresses findings of the listed rules on its own physical line.
A pragma in the file *prologue* — before any code, i.e. among shebang/coding
/comment/blank lines and the module docstring — suppresses the listed rules
for the whole file.

Shared infrastructure: sibling suites reuse the machinery under their own
marker (``parse_pragmas(source, tool="graftproto")`` recognizes
``# graftproto: disable=P006``); each suite only sees its own pragmas.
"""

from __future__ import annotations

import re
from typing import Dict, FrozenSet

_PRAGMA_RES: Dict[str, "re.Pattern[str]"] = {}


def pragma_re(tool: str = "graftlint") -> "re.Pattern[str]":
    pat = _PRAGMA_RES.get(tool)
    if pat is None:
        pat = _PRAGMA_RES[tool] = re.compile(
            rf"#\s*{re.escape(tool)}:\s*"
            r"disable=([A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)"
        )
    return pat


PRAGMA_RE = pragma_re("graftlint")

ALL = frozenset({"all"})

# sentinel key for file-level (prologue) pragmas in the parsed map
FILE_LEVEL = 0


def _prologue_end(lines) -> int:
    """Number of leading lines that are shebang/comments/blanks/docstring
    (plus comments/blanks after the docstring) — i.e. everything before the
    first line of actual code."""
    n = len(lines)

    def skip_trivia(i: int) -> int:
        while i < n and (not lines[i].strip()
                         or lines[i].lstrip().startswith("#")):
            i += 1
        return i

    i = skip_trivia(0)
    stripped = lines[i].lstrip() if i < n else ""
    for quote in ('"""', "'''"):
        if stripped.startswith(quote):
            rest = stripped[len(quote):]
            if quote not in rest:  # multi-line docstring
                i += 1
                while i < n and quote not in lines[i]:
                    i += 1
            i = min(i + 1, n)
            i = skip_trivia(i)
            break
    return i


def parse_pragmas(source: str,
                  tool: str = "graftlint") -> Dict[int, FrozenSet[str]]:
    """1-based line -> rules disabled there; key ``FILE_LEVEL`` (0) holds
    rules disabled for the whole file (pragma in the prologue)."""
    out: Dict[int, FrozenSet[str]] = {}
    pat = pragma_re(tool)
    lines = source.splitlines()
    prologue = _prologue_end(lines)
    for i, text in enumerate(lines, start=1):
        m = pat.search(text)
        if not m:
            continue
        rules = frozenset(
            r.strip() for r in m.group(1).split(",") if r.strip()
        )
        rules = ALL if "all" in rules else rules
        key = FILE_LEVEL if i <= prologue else i
        out[key] = out.get(key, frozenset()) | rules
    return out


def is_suppressed(pragmas: Dict[int, FrozenSet[str]], rule: str,
                  line: int) -> bool:
    for key in (line, FILE_LEVEL):
        rules = pragmas.get(key)
        if rules and ("all" in rules or rule in rules):
            return True
    return False
