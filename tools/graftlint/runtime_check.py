"""Runtime-assisted purity check: trace round functions under
``jax.make_jaxpr`` and inspect the result.

The static rules reason about syntax; this closes the loop on the real
artifact. A round function is accepted when

- tracing succeeds with abstract inputs (no data-dependent Python control
  flow / host sync that throws under trace),
- the closed jaxpr carries **no effects** (no ``debug_callback`` /
  ``io_callback`` / ``pure_callback`` equations anywhere, recursively),
- tracing produced **no stdout/stderr output** (a ``print`` that fires at
  trace time is a silent lie — it will never run again), and
- tracing twice yields the **same jaxpr** (a mismatch means global mutable
  state — RNG advances, counters — leaked into the trace).

``check_round_engine`` builds tiny FedAvg/FedOpt/SCAFFOLD configs the same
way the parity tests do and verifies ``round_engine.build_round_core``'s
program for each, so ``python -m tools.graftlint --runtime`` certifies the
actual fused round path, not a model of it.
"""

from __future__ import annotations

import contextlib
import io
import os
import sys
from typing import Any, Callable, List, Sequence

from .findings import Finding


def trace_purity_issues(fn: Callable, example_args: Sequence[Any],
                        name: str = "fn") -> List[str]:
    """Trace ``fn`` twice under ``jax.make_jaxpr``; return issue strings."""
    import jax

    issues: List[str] = []
    buf = io.StringIO()
    try:
        with contextlib.redirect_stdout(buf), contextlib.redirect_stderr(buf):
            # fresh wrapper objects per trace: jax caches the jaxpr on
            # function identity, which would hide nondeterministic traces
            closed1 = jax.make_jaxpr(lambda *a: fn(*a))(*example_args)
            closed2 = jax.make_jaxpr(lambda *a: fn(*a))(*example_args)
    except Exception as e:  # noqa: BLE001 - any trace failure is the finding
        return [f"{name}: tracing failed under jax.make_jaxpr: "
                f"{type(e).__name__}: {e}"]
    out = buf.getvalue()
    if out.strip():
        issues.append(
            f"{name}: tracing wrote to stdout/stderr ({out.strip()[:120]!r})"
            " — host I/O fires at trace time only"
        )
    effects = getattr(closed1, "effects", None)
    if effects:
        issues.append(f"{name}: jaxpr carries effects {sorted(map(str, effects))}")
    for prim in _callback_prims(closed1.jaxpr):
        issues.append(f"{name}: jaxpr contains host-callback primitive "
                      f"`{prim}`")
    consts_differ = len(closed1.consts) != len(closed2.consts) or any(
        not _consts_equal(a, b)
        for a, b in zip(closed1.consts, closed2.consts)
    )
    if str(closed1) != str(closed2) or consts_differ:
        issues.append(
            f"{name}: two traces produced different jaxprs — global mutable "
            "state (np.random, counters) leaked into the trace"
        )
    return issues


def _consts_equal(a: Any, b: Any) -> bool:
    try:
        import numpy as np

        return bool(np.array_equal(np.asarray(a), np.asarray(b)))
    except Exception:  # noqa: BLE001 - non-array consts: fall back
        return a is b or a == b


def _callback_prims(jaxpr) -> List[str]:
    found: List[str] = []

    def walk(jp) -> None:
        for eqn in jp.eqns:
            pname = str(eqn.primitive)
            if "callback" in pname:
                found.append(pname)
            for v in eqn.params.values():
                inner = getattr(v, "jaxpr", None)
                if inner is not None:
                    walk(inner)
                if isinstance(v, (list, tuple)):
                    for item in v:
                        inner = getattr(item, "jaxpr", None)
                        if inner is not None:
                            walk(inner)

    walk(jaxpr)
    return found


# ---------------------------------------------------------------------------
# Round-engine certification
# ---------------------------------------------------------------------------

_CONFIGS = (
    dict(federated_optimizer="FedAvg"),
    dict(federated_optimizer="FedOpt", server_optimizer="adam",
         server_lr=0.03),
    dict(federated_optimizer="SCAFFOLD"),
)


def _tiny_api(overrides: dict):
    import fedml_tpu as fedml
    from fedml_tpu import data as data_mod
    from fedml_tpu import models as model_mod
    from fedml_tpu.arguments import Arguments
    from fedml_tpu.simulation.sp_api import FedAvgAPI

    base = dict(
        dataset="synthetic", model="lr", client_num_in_total=8,
        client_num_per_round=4, comm_round=1, epochs=1, batch_size=8,
        learning_rate=0.1, round_fusion="off",
    )
    base.update(overrides)
    args = fedml.init(Arguments(overrides=base), should_init_logs=False)
    ds, od = data_mod.load(args)
    return FedAvgAPI(args, fedml.get_device(args), ds,
                     model_mod.create(args, od))


def check_round_engine(repo_root: str) -> List[Finding]:
    """Trace ``build_round_core`` for the tiny reference configs."""
    sys.path.insert(0, repo_root)
    try:
        import jax
        import jax.numpy as jnp
        import numpy as np

        from fedml_tpu.simulation.round_engine import build_round_core
    except Exception as e:  # pragma: no cover - env without the package
        # environment problem, not a lint finding — the CLI maps this to
        # exit code 2 so CI distinguishes "tool unavailable" from "impure"
        raise RuntimeError(
            f"graftlint --runtime unavailable: {type(e).__name__}: {e}"
        ) from e

    findings: List[Finding] = []
    rel = os.path.join("fedml_tpu", "simulation",
                       "round_engine.py").replace(os.sep, "/")
    for overrides in _CONFIGS:
        opt = overrides["federated_optimizer"]
        api = _tiny_api(overrides)
        per = min(int(api.args.client_num_per_round), api.ds.client_num)
        cohort = np.arange(per)
        cx, cy, cn = api._gather_cohort(cohort)
        rng = jax.random.fold_in(api.root_rng, 0)
        rngs = jax.random.split(rng, per)
        core = build_round_core(api, n_cohort=per, n_valid=per)
        state = api._round_state()
        issues = trace_purity_issues(
            core,
            (state, jnp.asarray(cohort, jnp.int32), cx, cy, cn, rngs, None,
             rng),
            name=f"build_round_core[{opt}]",
        )
        findings += [
            # line_text carries the issue so each distinct runtime failure
            # gets its own baseline key (path::rule::line_text) instead of
            # all of them collapsing onto one suppressible entry
            Finding(rule="G004", path=rel, line=1, col=0,
                    message=f"runtime purity check: {msg}",
                    line_text=f"runtime::{msg}")
            for msg in issues
        ]
    return findings
