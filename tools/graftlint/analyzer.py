"""AST core: module index, jit call graph, and rules G001–G004.

No imports of the analyzed code ever happen — everything is syntactic:

1. **Index** every module under the scan roots (functions, classes, imports).
2. **Trace roots**: functions that reach an XLA trace — ``@jax.jit``
   decorators, ``jax.jit(f)`` / ``lax.scan(f, ...)`` sites, factory returns
   (``return jax.jit(core, ...)`` where ``core`` came from a package factory
   like ``round_engine.build_round_core``), plus the explicit seed list.
3. **Propagation**: BFS over call edges (local names, package imports, and a
   conservative class-hierarchy match on distinctive method names) marks the
   trace-reachable set.
4. **Checkers**: G001 (host syncs on tainted values inside traced code),
   G002 (use-after-donate, in *any* function), G003 (recompile hazards at
   jit boundaries), G004 (side effects inside traced code).

G005 lives in :mod:`tools.graftlint.threads`.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .findings import Finding

# functions whose nested defs are always treated as traced, even if no jit
# site is syntactically resolvable (the round engine's factory indirection)
SEED_FACTORIES = ("build_round_core",)

# single-function tracing transforms: transform(f) traces f
TRACING_SINGLE = {
    "jit", "vmap", "pmap", "grad", "value_and_grad", "checkpoint", "remat",
    "make_jaxpr", "eval_shape", "custom_jvp", "custom_vjp", "jacrev",
    "jacfwd", "hessian", "linearize",
}

# lax control-flow HOFs: which positional args are traced bodies
LAX_HOF_POS = {
    "scan": (0,), "map": (0,), "associative_scan": (0,),
    "fori_loop": (2,), "while_loop": (0, 1), "cond": (1, 2, 3),
    "switch": (1,),
}

# host-sync builtins flagged by G001 when fed a traced (tainted) value
HOST_CASTS = {"float", "int", "bool", "complex"}

# attribute reads that yield static (host) metadata — taint stops here
STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "itemsize", "nbytes"}

# method names too generic for class-hierarchy call-graph matching
CHA_STOPLIST = {
    "get", "put", "update", "add", "items", "keys", "values", "close",
    "run", "start", "stop", "join", "send", "recv", "append", "pop",
    "init", "save", "restore", "reset", "flush", "read", "write", "open",
    "load", "serialize", "deserialize", "copy", "apply", "call", "sum",
    "mean", "max", "min", "split", "replace", "count", "index", "extend",
    "remove", "insert", "sort", "setdefault", "clear",
}
CHA_LIMIT = 8  # skip method names with more definitions than this

MUTATORS_ATTR = {
    # "update" stays out: optax GradientTransformation.update (pure, and all
    # over the traced optimizer paths) is indistinguishable from dict.update
    "append", "extend", "insert", "pop", "popitem", "clear",
    "setdefault", "remove", "discard", "add", "write", "put",
}
MUTATORS_BARE = {"append", "extend", "insert", "popitem", "setdefault"}

LOG_METHODS = {"debug", "info", "warning", "warn", "error", "exception",
               "critical", "log"}


def dotted(node: ast.AST) -> Optional[str]:
    """Name/Attribute chain → ``a.b.c`` (None for anything else)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class FuncInfo:
    __slots__ = (
        "module", "node", "qualname", "parent", "class_name", "nested",
        "returned", "returns_donated", "donate_argnums", "returns_strictjit",
        "traced", "edges",
    )

    def __init__(self, module: "ModuleInfo", node: ast.AST, qualname: str,
                 parent: Optional["FuncInfo"], class_name: Optional[str]):
        self.module = module
        self.node = node
        self.qualname = qualname
        self.parent = parent
        self.class_name = class_name
        self.nested: Dict[str, FuncInfo] = {}
        self.returned: List[FuncInfo] = []
        self.returns_donated = False
        self.donate_argnums: Optional[Tuple[int, ...]] = None
        self.returns_strictjit = False
        self.traced = False
        self.edges: Set[FuncInfo] = set()

    @property
    def name(self) -> str:
        return self.qualname.rsplit(".", 1)[-1]

    def params(self) -> List[str]:
        a = self.node.args
        names = [p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)]
        if a.vararg:
            names.append(a.vararg.arg)
        if a.kwarg:
            names.append(a.kwarg.arg)
        return names


class ModuleInfo:
    def __init__(self, path: str, rel: str, name: str, tree: ast.Module,
                 source: str, is_package: bool = False):
        self.path = path
        self.rel = rel
        self.name = name
        self.is_package = is_package
        self.tree = tree
        self.source = source
        self.lines = source.splitlines()
        self.imports: Dict[str, str] = {}        # alias -> dotted module
        self.from_imports: Dict[str, Tuple[str, str]] = {}  # name -> (mod, orig)
        self.funcs_by_node: Dict[int, FuncInfo] = {}
        self.toplevel: Dict[str, FuncInfo] = {}
        self.classes: Dict[str, Dict[str, FuncInfo]] = {}
        self.class_bases: Dict[str, List[str]] = {}

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""


class PackageIndex:
    def __init__(self, modules: Dict[str, ModuleInfo]):
        self.modules = modules
        self.all_methods: Dict[str, List[FuncInfo]] = {}
        for mod in modules.values():
            for methods in mod.classes.values():
                for m in methods.values():
                    self.all_methods.setdefault(m.name, []).append(m)
        # attr name -> donate_argnums for donated jit programs bound via
        # ``self.attr = factory(...)`` (filled during fact passes)
        self.donating_attrs: Dict[str, Optional[Tuple[int, ...]]] = {}
        self.strictjit_attrs: Set[str] = set()


# ---------------------------------------------------------------------------
# Loading
# ---------------------------------------------------------------------------


def collect_files(paths: Sequence[str]) -> List[str]:
    files: List[str] = []
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p) and p.endswith(".py"):
            files.append(p)
        elif os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = [d for d in dirs
                           if d not in ("__pycache__", ".git", ".venv")]
                files.extend(os.path.join(root, n) for n in sorted(names)
                             if n.endswith(".py"))
    return sorted(set(files))


def module_name_for(path: str, repo_root: str) -> str:
    rel = os.path.relpath(path, repo_root)
    parts = rel.replace(os.sep, "/").split("/")
    if parts[-1] == "__init__.py":
        parts = parts[:-1]
    else:
        parts[-1] = parts[-1][:-3]
    return ".".join(parts)


def load_modules(files: Sequence[str], repo_root: str
                 ) -> Dict[str, ModuleInfo]:
    modules: Dict[str, ModuleInfo] = {}
    for path in files:
        with open(path, encoding="utf-8") as f:
            source = f.read()
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError:
            continue
        rel = os.path.relpath(path, repo_root).replace(os.sep, "/")
        name = module_name_for(path, repo_root)
        mod = ModuleInfo(path, rel, name, tree, source,
                         is_package=path.endswith("__init__.py"))
        _collect_imports(mod)
        _collect_funcs(mod)
        modules[name] = mod
    return modules


def _collect_imports(mod: ModuleInfo) -> None:
    # the package containing this module: for a/b/c.py that's a.b; for
    # a/b/__init__.py the module name a.b IS the package — level 1 resolves
    # against it directly, not against a
    parts = mod.name.split(".")
    pkg_parts = parts if mod.is_package else parts[:-1]
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                mod.imports[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                base_parts = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                base = ".".join(base_parts)
                if node.module:
                    base = f"{base}.{node.module}" if base else node.module
            else:
                base = node.module or ""
            for a in node.names:
                local = a.asname or a.name
                mod.from_imports[local] = (base, a.name)


def _collect_funcs(mod: ModuleInfo) -> None:
    def walk(node: ast.AST, parent: Optional[FuncInfo],
             class_name: Optional[str], prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                fi = FuncInfo(mod, child, qual, parent, class_name)
                mod.funcs_by_node[id(child)] = fi
                if parent is not None:
                    parent.nested[child.name] = fi
                elif class_name is not None:
                    mod.classes.setdefault(class_name, {})[child.name] = fi
                else:
                    mod.toplevel[child.name] = fi
                walk(child, fi, None, qual + ".")
            elif isinstance(child, ast.Lambda):
                qual = f"{prefix}<lambda:{child.lineno}>"
                fi = FuncInfo(mod, child, qual, parent, class_name)
                mod.funcs_by_node[id(child)] = fi
                if parent is not None:
                    parent.nested[f"<lambda:{child.lineno}>"] = fi
                walk(child, fi, None, qual + ".")
            elif isinstance(child, ast.ClassDef):
                mod.classes.setdefault(child.name, {})
                mod.class_bases[child.name] = [
                    d for d in (dotted(b) for b in child.bases) if d
                ]
                walk(child, parent, child.name, f"{prefix}{child.name}.")
            else:
                walk(child, parent, class_name, prefix)

    walk(mod.tree, None, None, "")


# ---------------------------------------------------------------------------
# jax-name classification
# ---------------------------------------------------------------------------


def _is_jaxish(mod: ModuleInfo, head: str) -> bool:
    if head == "jax":
        return True
    tgt = mod.imports.get(head, "")
    if tgt.startswith("jax"):
        return True
    fi = mod.from_imports.get(head)
    return bool(fi and fi[0].startswith("jax"))


def _is_numpy(mod: ModuleInfo, head: str) -> bool:
    return head == "numpy" or mod.imports.get(head, "") == "numpy"


def _hof_positions(mod: ModuleInfo, ds: str) -> Optional[Tuple[int, ...]]:
    parts = ds.split(".")
    last = parts[-1]
    if last in TRACING_SINGLE:
        if len(parts) == 1:
            fi = mod.from_imports.get(last)
            if fi and fi[0].startswith("jax"):
                return (0,)
            return None
        if _is_jaxish(mod, parts[0]):
            return (0,)
        return None
    if last in LAX_HOF_POS:
        if "lax" in parts[:-1]:
            return LAX_HOF_POS[last]
        if len(parts) >= 2 and _is_jaxish(mod, parts[0]):
            tgt = mod.imports.get(parts[0], "")
            if parts[-2] == "lax" or tgt.endswith("lax"):
                return LAX_HOF_POS[last]
    return None


def _jit_call_info(mod: ModuleInfo, call: ast.Call
                   ) -> Optional[Tuple[Optional[ast.expr], bool,
                                       Optional[Tuple[int, ...]], bool]]:
    """If ``call`` is a ``jax.jit(...)`` call: (fn_expr, has_static,
    donate_argnums, is_donating). fn_expr is None for decorator factories."""
    ds = dotted(call.func)
    if ds is None:
        return None
    last = ds.split(".")[-1]
    is_partial = last == "partial"
    if is_partial:
        if not call.args:
            return None
        inner = dotted(call.args[0])
        if not inner or inner.split(".")[-1] != "jit":
            return None
        if not _is_jaxish(mod, inner.split(".")[0]) and inner != "jit":
            return None
        fn_expr = call.args[1] if len(call.args) > 1 else None
    else:
        if last != "jit" or _hof_positions(mod, ds) is None:
            return None
        fn_expr = call.args[0] if call.args else None
    has_static = donating = False
    argnums: Optional[Tuple[int, ...]] = None
    for kw in call.keywords:
        if kw.arg in ("static_argnums", "static_argnames"):
            has_static = True
        elif kw.arg in ("donate_argnums", "donate_argnames"):
            donating = True
            argnums = _parse_argnums(kw.value)
    return fn_expr, has_static, argnums, donating


def _parse_argnums(node: ast.expr) -> Optional[Tuple[int, ...]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
            else:
                return None
        return tuple(out)
    return None


# ---------------------------------------------------------------------------
# Fact passes: returned funcs, donating callables, trace roots, call edges
# ---------------------------------------------------------------------------


class _Env:
    """Per-function syntactic facts about local names."""

    __slots__ = ("returned_locals", "donating_locals", "strictjit_locals")

    def __init__(self):
        self.returned_locals: Dict[str, List[FuncInfo]] = {}
        # name -> donate_argnums (None = unknown positions, still donating)
        self.donating_locals: Dict[str, Optional[Tuple[int, ...]]] = {}
        self.strictjit_locals: Set[str] = set()


class Analyzer:
    def __init__(self, modules: Dict[str, ModuleInfo]):
        self.modules = modules
        self.index = PackageIndex(modules)
        self.envs: Dict[FuncInfo, _Env] = {}
        self.module_envs: Dict[ModuleInfo, _Env] = {}
        self.findings: List[Finding] = []

    # -- resolution ---------------------------------------------------------
    def _all_funcs(self) -> List[FuncInfo]:
        return [f for m in self.modules.values()
                for f in m.funcs_by_node.values()]

    def resolve_name(self, mod: ModuleInfo, scope: Optional[FuncInfo],
                     name: str) -> List[FuncInfo]:
        f = scope
        while f is not None:
            if name in f.nested:
                return [f.nested[name]]
            env = self.envs.get(f)
            if env and name in env.returned_locals:
                return env.returned_locals[name]
            f = f.parent
        menv = self.module_envs.get(mod)
        if menv and name in menv.returned_locals:
            return menv.returned_locals[name]
        if name in mod.toplevel:
            return [mod.toplevel[name]]
        fi = mod.from_imports.get(name)
        if fi:
            target = self.modules.get(fi[0])
            if target and fi[1] in target.toplevel:
                return [target.toplevel[fi[1]]]
        return []

    def resolve_call_targets(self, mod: ModuleInfo, scope: Optional[FuncInfo],
                             call: ast.Call) -> List[FuncInfo]:
        func = call.func
        if isinstance(func, ast.Name):
            return self.resolve_name(mod, scope, func.id)
        if isinstance(func, ast.Attribute):
            base = func.value
            # module-qualified package function: pkgmod.fn(...)
            if isinstance(base, ast.Name):
                tgt = mod.imports.get(base.id)
                if tgt is None and base.id in mod.from_imports:
                    b, orig = mod.from_imports[base.id]
                    full = f"{b}.{orig}" if b else orig
                    tgt = full if full in self.modules else None
                if tgt and tgt in self.modules:
                    target = self.modules[tgt]
                    if func.attr in target.toplevel:
                        return [target.toplevel[func.attr]]
                    return []
                # self.method(...) within a class
                if base.id == "self" and scope is not None:
                    f = scope
                    while f is not None and f.class_name is None:
                        f = f.parent
                    if f is not None and f.class_name:
                        methods = f.module.classes.get(f.class_name, {})
                        if func.attr in methods:
                            return [methods[func.attr]]
            # conservative CHA on distinctive method names
            m = func.attr
            if (m not in CHA_STOPLIST and not m.startswith("__")):
                # skip known-external receivers (jnp.mean, np.stack, ...)
                if isinstance(base, ast.Name) and (
                    _is_jaxish(mod, base.id) or _is_numpy(mod, base.id)
                    or mod.imports.get(base.id, "").split(".")[0]
                    in ("optax", "flax", "grpc", "orbax", "logging")
                ):
                    return []
                cands = self.index.all_methods.get(m, [])
                if 0 < len(cands) <= CHA_LIMIT:
                    return list(cands)
        return []

    def _resolve_fn_expr(self, mod: ModuleInfo, scope: Optional[FuncInfo],
                         expr: ast.expr) -> List[FuncInfo]:
        """Resolve an expression in a traced-function position."""
        if isinstance(expr, ast.Lambda):
            fi = mod.funcs_by_node.get(id(expr))
            return [fi] if fi else []
        if isinstance(expr, ast.Name):
            return self.resolve_name(mod, scope, expr.id)
        if isinstance(expr, ast.Call):
            ds = dotted(expr.func)
            if ds is not None and _hof_positions(mod, ds) is not None:
                out: List[FuncInfo] = []
                for pos in _hof_positions(mod, ds):
                    if pos < len(expr.args):
                        out += self._resolve_fn_expr(mod, scope,
                                                     expr.args[pos])
                return out
            # factory call: f() where f returns traced funcs
            targets = self.resolve_call_targets(mod, scope, expr)
            out = []
            for t in targets:
                out += t.returned
            return out
        if isinstance(expr, (ast.Tuple, ast.List)):
            out = []
            for e in expr.elts:
                out += self._resolve_fn_expr(mod, scope, e)
            return out
        return []

    # -- fixpoint fact computation -----------------------------------------
    def compute_facts(self) -> None:
        for _ in range(5):
            changed = False
            for mod in self.modules.values():
                menv = self.module_envs.setdefault(mod, _Env())
                for node in _walk_shallow(mod.tree):
                    if (isinstance(node, ast.Assign)
                            and len(node.targets) == 1):
                        changed |= self._record_assignment(
                            mod, None, menv, node.targets[0], node.value)
                for fi in mod.funcs_by_node.values():
                    changed |= self._func_facts(mod, fi)
                changed |= self._scan_sites(mod, None, mod.tree)
            if not changed:
                break

    def _func_facts(self, mod: ModuleInfo, fi: FuncInfo) -> bool:
        changed = False
        env = self.envs.setdefault(fi, _Env())
        for node in _walk_shallow(fi.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                changed |= self._record_assignment(
                    mod, fi, env, node.targets[0], node.value)
            elif isinstance(node, ast.Return) and node.value is not None:
                changed |= self._record_return(mod, fi, node.value)
        changed |= self._scan_sites(mod, fi, fi.node)
        return changed

    def _record_assignment(self, mod: ModuleInfo, fi: FuncInfo, env: _Env,
                           target: ast.expr, value: ast.expr) -> bool:
        changed = False
        info = (isinstance(value, ast.Call)
                and _jit_call_info(mod, value)) or None
        if info:
            fn_expr, has_static, argnums, donating = info
            if fn_expr is not None:
                for t in self._resolve_fn_expr(mod, fi, fn_expr):
                    if not t.traced:
                        t.traced = changed = True
            if isinstance(target, ast.Name):
                if donating and target.id not in env.donating_locals:
                    env.donating_locals[target.id] = argnums
                    changed = True
                if not has_static and target.id not in env.strictjit_locals:
                    env.strictjit_locals.add(target.id)
                    changed = True
            elif (isinstance(target, ast.Attribute)
                  and isinstance(target.value, ast.Name)
                  and target.value.id == "self"):
                if donating and target.attr not in self.index.donating_attrs:
                    self.index.donating_attrs[target.attr] = argnums
                    changed = True
                if (not has_static
                        and target.attr not in self.index.strictjit_attrs):
                    self.index.strictjit_attrs.add(target.attr)
                    changed = True
            return changed
        if isinstance(value, ast.Call):
            targets = self.resolve_call_targets(mod, fi, value)
            returned: List[FuncInfo] = []
            donated = None
            any_donating = any_strict = False
            for t in targets:
                returned += t.returned
                if t.returns_donated:
                    any_donating = True
                    donated = t.donate_argnums
                if t.returns_strictjit:
                    any_strict = True
            if isinstance(target, ast.Name):
                if returned and target.id not in env.returned_locals:
                    env.returned_locals[target.id] = returned
                    changed = True
                if any_donating and target.id not in env.donating_locals:
                    env.donating_locals[target.id] = donated
                    changed = True
                if any_strict and target.id not in env.strictjit_locals:
                    env.strictjit_locals.add(target.id)
                    changed = True
            elif (isinstance(target, ast.Attribute)
                  and isinstance(target.value, ast.Name)
                  and target.value.id == "self"):
                if (any_donating
                        and target.attr not in self.index.donating_attrs):
                    self.index.donating_attrs[target.attr] = donated
                    changed = True
                if any_strict and target.attr not in self.index.strictjit_attrs:
                    self.index.strictjit_attrs.add(target.attr)
                    changed = True
        return changed

    def _record_return(self, mod: ModuleInfo, fi: FuncInfo,
                       value: ast.expr) -> bool:
        changed = False
        if isinstance(value, ast.Call):
            info = _jit_call_info(mod, value)
            if info:
                fn_expr, _has_static, argnums, donating = info
                resolved = (self._resolve_fn_expr(mod, fi, fn_expr)
                            if fn_expr is not None else [])
                for t in resolved:
                    if not t.traced:
                        t.traced = changed = True
                    if t not in fi.returned:
                        fi.returned.append(t)
                        changed = True
                if donating and not fi.returns_donated:
                    fi.returns_donated = True
                    fi.donate_argnums = argnums
                    changed = True
                if not info[1] and not fi.returns_strictjit:
                    fi.returns_strictjit = True
                    changed = True
                return changed
        for t in self._resolve_fn_expr(mod, fi, value):
            if t not in fi.returned:
                fi.returned.append(t)
                changed = True
        return changed

    def _scan_sites(self, mod: ModuleInfo, scope: Optional[FuncInfo],
                    root: ast.AST) -> bool:
        """Mark traced roots at jit/HOF sites + decorators under ``root``
        (not descending into nested function bodies)."""
        changed = False
        for node in _walk_shallow(root):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fi = mod.funcs_by_node.get(id(node))
                if fi is None:
                    continue
                for dec in node.decorator_list:
                    if self._decorator_traces(mod, dec) and not fi.traced:
                        fi.traced = changed = True
                if (node.name in SEED_FACTORIES
                        or fi.name in SEED_FACTORIES):
                    for sub in fi.nested.values():
                        if not sub.traced:
                            sub.traced = changed = True
            elif isinstance(node, ast.Call):
                ds = dotted(node.func)
                if ds is None:
                    continue
                positions = _hof_positions(mod, ds)
                if positions is None:
                    info = _jit_call_info(mod, node)
                    if info and info[0] is not None:
                        for t in self._resolve_fn_expr(mod, scope, info[0]):
                            if not t.traced:
                                t.traced = changed = True
                    continue
                for pos in positions:
                    if pos < len(node.args):
                        for t in self._resolve_fn_expr(mod, scope,
                                                       node.args[pos]):
                            if not t.traced:
                                t.traced = changed = True
        return changed

    def _decorator_traces(self, mod: ModuleInfo, dec: ast.expr) -> bool:
        ds = dotted(dec)
        if ds is not None:
            return _hof_positions(mod, ds) == (0,)
        if isinstance(dec, ast.Call):
            info = _jit_call_info(mod, dec)
            return info is not None
        return False

    # -- traced propagation -------------------------------------------------
    def propagate(self) -> None:
        for mod in self.modules.values():
            for fi in mod.funcs_by_node.values():
                self._compute_edges(mod, fi)
        work = [f for f in self._all_funcs() if f.traced]
        seen = set(work)
        while work:
            f = work.pop()
            # nested lambdas of a traced function execute during its trace
            # (jax.tree.map(lambda ...) bodies etc.)
            lambdas = [n for name, n in f.nested.items()
                       if name.startswith("<lambda")]
            for t in list(f.edges) + lambdas:
                if not t.traced:
                    t.traced = True
                if t not in seen:
                    seen.add(t)
                    work.append(t)

    def _compute_edges(self, mod: ModuleInfo, fi: FuncInfo) -> None:
        for node in _walk_shallow(fi.node):
            if isinstance(node, ast.Call):
                for t in self.resolve_call_targets(mod, fi, node):
                    fi.edges.add(t)
                ds = dotted(node.func)
                if ds is not None:
                    positions = _hof_positions(mod, ds)
                    if positions:
                        for pos in positions:
                            if pos < len(node.args):
                                for t in self._resolve_fn_expr(
                                        mod, fi, node.args[pos]):
                                    fi.edges.add(t)

    # -- entry --------------------------------------------------------------
    def run(self) -> List[Finding]:
        self.compute_facts()
        self.propagate()
        from .rules import check_function, check_untraced
        for mod in self.modules.values():
            for fi in mod.funcs_by_node.values():
                if fi.traced:
                    self.findings += check_function(self, mod, fi)
                self.findings += check_untraced(self, mod, fi)
        from .threads import check_module_threads
        thread_entries = _collect_thread_entries(self.modules)
        for mod in self.modules.values():
            self.findings += check_module_threads(mod, thread_entries)
        return self.findings


def _walk_shallow(root: ast.AST):
    """Yield nodes under ``root`` without entering nested function bodies."""
    stack = [c for c in ast.iter_child_nodes(root)]
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # decorators/defaults still belong to this scope
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                stack.extend(node.decorator_list)
            continue
        stack.extend(ast.iter_child_nodes(node))


def _collect_thread_entries(modules: Dict[str, ModuleInfo]) -> Set[str]:
    """Method names used as ``threading.Thread(target=...)`` anywhere."""
    names: Set[str] = set()
    for mod in modules.values():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            ds = dotted(node.func)
            if not ds or not ds.split(".")[-1] == "Thread":
                continue
            for kw in node.keywords:
                if kw.arg == "target":
                    tds = dotted(kw.value)
                    if tds:
                        names.add(tds.split(".")[-1])
    return names


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


def analyze_paths(paths: Sequence[str],
                  repo_root: Optional[str] = None) -> List[Finding]:
    """Analyze files/dirs → pragma-filtered findings (baseline NOT applied)."""
    from .baseline import find_repo_root
    from .pragmas import is_suppressed, parse_pragmas

    if repo_root is None:
        repo_root = find_repo_root(paths[0] if paths else os.getcwd())
    files = collect_files(paths)
    modules = load_modules(files, repo_root)
    findings = Analyzer(modules).run()
    out: List[Finding] = []
    pragma_cache: Dict[str, Dict] = {}
    mods_by_rel = {m.rel: m for m in modules.values()}
    for f in findings:
        mod = mods_by_rel.get(f.path)
        if mod is not None:
            pragmas = pragma_cache.setdefault(f.path,
                                              parse_pragmas(mod.source))
            if is_suppressed(pragmas, f.rule, f.line):
                continue
        out.append(f)
    return sorted(out, key=lambda f: (f.path, f.line, f.col, f.rule))
