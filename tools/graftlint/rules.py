"""Rule checkers G001–G004 over the analyzed function set.

``check_function`` runs the traced-code rules (G001 host syncs on tainted
values, G004 impurity) on functions the call graph marked trace-reachable;
``check_untraced`` runs the host-side rules (G002 use-after-donate, G003
recompile hazards) on every function.

Taint model (G001): a traced function's parameters are tracers; taint flows
through assignments/comprehensions and stops at static metadata
(``.shape``/``.dtype``/``len()``) and at host casts themselves (the cast IS
the finding; its result is a host value).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .analyzer import (
    CHA_STOPLIST,
    HOST_CASTS,
    LOG_METHODS,
    MUTATORS_ATTR,
    MUTATORS_BARE,
    STATIC_ATTRS,
    Analyzer,
    FuncInfo,
    ModuleInfo,
    _is_jaxish,
    _is_numpy,
    _jit_call_info,
    dotted,
)
from .findings import Finding


def _mk(mod: ModuleInfo, rule: str, node: ast.AST, message: str) -> Finding:
    line = getattr(node, "lineno", 1)
    col = getattr(node, "col_offset", 0)
    return Finding(rule=rule, path=mod.rel, line=line, col=col,
                   message=message, line_text=mod.line_text(line))


def _root_name(node: ast.expr) -> Optional[str]:
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _body_of(fi: FuncInfo) -> List[ast.stmt]:
    if isinstance(fi.node, ast.Lambda):
        return [ast.Expr(fi.node.body)]
    return fi.node.body


# ---------------------------------------------------------------------------
# G001 + G004: traced-function checker
# ---------------------------------------------------------------------------


class _TraceChecker:
    def __init__(self, analyzer: Analyzer, mod: ModuleInfo, fi: FuncInfo):
        self.an = analyzer
        self.mod = mod
        self.fi = fi
        self.findings: List[Finding] = []
        params = fi.params()
        self.tainted: Set[str] = {p for p in params if p not in ("self", "cls")}
        self.local_created: Set[str] = set()
        self.record = False

    # -- taint --------------------------------------------------------------
    def expr_tainted(self, e: Optional[ast.expr]) -> bool:
        if e is None:
            return False
        if isinstance(e, ast.Name):
            return e.id in self.tainted
        if isinstance(e, ast.Attribute):
            if e.attr in STATIC_ATTRS:
                return False
            return self.expr_tainted(e.value)
        if isinstance(e, ast.Call):
            ds = dotted(e.func)
            if ds == "len":
                return False
            if ds in HOST_CASTS:
                return False  # host value; the cast itself is the finding
            if isinstance(e.func, ast.Attribute) and e.func.attr in (
                    "item", "tolist"):
                return False
            if ds is not None:
                parts = ds.split(".")
                if (len(parts) > 1 and _is_numpy(self.mod, parts[0])
                        and parts[-1] in ("asarray", "array")):
                    return False
            # taint flows through method-call receivers (x.sum(), x.mean())
            recv_tainted = (self.expr_tainted(e.func.value)
                            if isinstance(e.func, ast.Attribute) else False)
            return recv_tainted or any(
                self.expr_tainted(a) for a in e.args
            ) or any(self.expr_tainted(k.value) for k in e.keywords)
        if isinstance(e, ast.expr):
            return any(self.expr_tainted(c)
                       for c in ast.iter_child_nodes(e)
                       if isinstance(c, ast.expr))
        return False

    def _taint_target(self, t: ast.expr, tainted: bool) -> None:
        if isinstance(t, ast.Name):
            self.local_created.add(t.id)
            if tainted:
                self.tainted.add(t.id)
            else:
                self.tainted.discard(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._taint_target(e, tainted)
        elif isinstance(t, ast.Starred):
            self._taint_target(t.value, tainted)

    # -- traversal ----------------------------------------------------------
    def run(self) -> List[Finding]:
        body = _body_of(self.fi)
        self.record = False
        self._visit_block(body)  # pass 1: taint fixpoint (loops)
        self.record = True
        self._visit_block(body)
        return self.findings

    def _visit_block(self, stmts: List[ast.stmt]) -> None:
        for s in stmts:
            self._visit_stmt(s)

    def _visit_stmt(self, s: ast.stmt) -> None:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.local_created.add(s.name)
            return  # nested defs are checked separately if traced
        if isinstance(s, ast.ClassDef):
            self.local_created.add(s.name)
            return
        if isinstance(s, ast.Assign):
            self._visit_expr(s.value)
            tainted = self.expr_tainted(s.value)
            for t in s.targets:
                if isinstance(t, (ast.Name, ast.Tuple, ast.List, ast.Starred)):
                    self._taint_target(t, tainted)
                else:
                    self._check_store_target(t, s)
                    self._visit_expr(t)
            return
        if isinstance(s, ast.AugAssign):
            self._visit_expr(s.value)
            if isinstance(s.target, ast.Name):
                if self.expr_tainted(s.value):
                    self.tainted.add(s.target.id)
                self.local_created.add(s.target.id)
            else:
                self._check_store_target(s.target, s)
            return
        if isinstance(s, ast.AnnAssign):
            if s.value is not None:
                self._visit_expr(s.value)
                if isinstance(s.target, ast.Name):
                    self._taint_target(s.target, self.expr_tainted(s.value))
                else:
                    self._check_store_target(s.target, s)
            return
        if isinstance(s, (ast.For, ast.AsyncFor)):
            self._visit_expr(s.iter)
            self._taint_target(s.target, self.expr_tainted(s.iter))
            self._visit_block(s.body)
            self._visit_block(s.orelse)
            return
        if isinstance(s, ast.While):
            self._visit_expr(s.test)
            self._visit_block(s.body)
            self._visit_block(s.orelse)
            return
        if isinstance(s, ast.If):
            self._visit_expr(s.test)
            self._visit_block(s.body)
            self._visit_block(s.orelse)
            return
        if isinstance(s, (ast.With, ast.AsyncWith)):
            for item in s.items:
                self._visit_expr(item.context_expr)
                if item.optional_vars is not None:
                    self._taint_target(item.optional_vars, True)
            self._visit_block(s.body)
            return
        if isinstance(s, ast.Try):
            self._visit_block(s.body)
            for h in s.handlers:
                if h.name:
                    self.local_created.add(h.name)
                self._visit_block(h.body)
            self._visit_block(s.orelse)
            self._visit_block(s.finalbody)
            return
        if isinstance(s, ast.Global):
            if self.record:
                self.findings.append(_mk(
                    self.mod, "G004", s,
                    f"`global {', '.join(s.names)}` inside traced "
                    f"`{self.fi.qualname}` — writes escape the trace",
                ))
            return
        if isinstance(s, ast.Return) and s.value is not None:
            self._visit_expr(s.value)
            return
        if isinstance(s, ast.Expr):
            self._visit_expr(s.value)
            return
        if isinstance(s, ast.Delete):
            for t in s.targets:
                if not isinstance(t, ast.Name):
                    self._check_store_target(t, s)
            return
        for child in ast.iter_child_nodes(s):
            if isinstance(child, ast.expr):
                self._visit_expr(child)
            elif isinstance(child, ast.stmt):
                self._visit_stmt(child)

    def _check_store_target(self, target: ast.expr, stmt: ast.stmt) -> None:
        if not self.record:
            return
        root = _root_name(target)
        if root is not None and root in self.local_created:
            return
        kind = ("attribute" if isinstance(target, ast.Attribute)
                else "container")
        name = dotted(target) or (f"{root}[...]" if root else "<expr>")
        self.findings.append(_mk(
            self.mod, "G004", stmt,
            f"{kind} write to `{name}` inside traced `{self.fi.qualname}` "
            "— side effect runs at trace time only and escapes the program",
        ))

    def _visit_expr(self, e: Optional[ast.expr]) -> None:
        if e is None:
            return
        if isinstance(e, ast.Call):
            self._visit_call(e)
            return
        if isinstance(e, ast.Lambda):
            return
        if isinstance(e, (ast.ListComp, ast.SetComp, ast.DictComp,
                          ast.GeneratorExp)):
            for gen in e.generators:
                self._visit_expr(gen.iter)
                self._taint_target(gen.target, self.expr_tainted(gen.iter))
                for cond in gen.ifs:
                    self._visit_expr(cond)
            if isinstance(e, ast.DictComp):
                self._visit_expr(e.key)
                self._visit_expr(e.value)
            else:
                self._visit_expr(e.elt)
            return
        for child in ast.iter_child_nodes(e):
            if isinstance(child, ast.expr):
                self._visit_expr(child)

    def _visit_call(self, call: ast.Call) -> None:
        for a in call.args:
            self._visit_expr(a)
        for k in call.keywords:
            self._visit_expr(k.value)
        if not self.record:
            return
        ds = dotted(call.func)
        parts = ds.split(".") if ds else []
        where = f"inside traced `{self.fi.qualname}`"

        # G001: host syncs
        if ds == "print":
            self.findings.append(_mk(
                self.mod, "G001", call,
                f"print() {where} runs at trace time only (use "
                "jax.debug.print, or log outside the traced region)",
            ))
        elif ds in HOST_CASTS and any(self.expr_tainted(a)
                                      for a in call.args):
            self.findings.append(_mk(
                self.mod, "G001", call,
                f"{ds}() on a traced value {where} forces a host sync "
                "(keep the scalar on device)",
            ))
        elif (isinstance(call.func, ast.Attribute)
              and call.func.attr in ("item", "tolist")
              and self.expr_tainted(call.func.value)):
            self.findings.append(_mk(
                self.mod, "G001", call,
                f".{call.func.attr}() on a traced value {where} forces a "
                "host sync",
            ))
        elif (len(parts) > 1 and _is_numpy(self.mod, parts[0])
              and parts[-1] in ("asarray", "array")
              and any(self.expr_tainted(a) for a in call.args)):
            self.findings.append(_mk(
                self.mod, "G001", call,
                f"{ds}() on a traced value {where} pulls the buffer to "
                "host (use jnp, or move this out of the traced region)",
            ))
        elif (parts and parts[-1] == "device_get"
              and _is_jaxish(self.mod, parts[0])
              and any(self.expr_tainted(a) for a in call.args)):
            self.findings.append(_mk(
                self.mod, "G001", call,
                f"jax.device_get() {where} forces a host sync",
            ))

        # G004: telemetry / logging / captured-state mutation
        if len(parts) >= 2 and parts[-2] == "telemetry" and parts[-1] != "phase":
            self.findings.append(_mk(
                self.mod, "G004", call,
                f"telemetry call `{ds}` {where} fires at trace time only — "
                "move it to the host-side wrapper",
            ))
        elif (len(parts) == 2 and parts[0] in ("logger", "logging")
              and parts[1] in LOG_METHODS):
            self.findings.append(_mk(
                self.mod, "G004", call,
                f"logging call `{ds}` {where} fires at trace time only",
            ))
        elif isinstance(call.func, ast.Attribute):
            recv = call.func.value
            m = call.func.attr
            if isinstance(recv, ast.Attribute):
                root = _root_name(recv)
                if (root is not None and root not in self.local_created
                        and m in MUTATORS_ATTR):
                    self.findings.append(_mk(
                        self.mod, "G004", call,
                        f"`{dotted(recv)}.{m}(...)` mutates captured state "
                        f"{where}",
                    ))
            elif isinstance(recv, ast.Name):
                if (recv.id not in self.local_created
                        and recv.id not in ("self", "cls")
                        and m in MUTATORS_BARE):
                    self.findings.append(_mk(
                        self.mod, "G004", call,
                        f"`{recv.id}.{m}(...)` mutates captured state "
                        f"{where}",
                    ))


def check_function(analyzer: Analyzer, mod: ModuleInfo,
                   fi: FuncInfo) -> List[Finding]:
    return _TraceChecker(analyzer, mod, fi).run()


# ---------------------------------------------------------------------------
# G002: use-after-donate (any function)
# ---------------------------------------------------------------------------


def _terminates(block: List[ast.stmt]) -> bool:
    """Whether a block's tail cannot fall through (return/raise/...).

    Shared infrastructure: graftrep's D001 branch join reuses this so an
    ``if … return`` arm's key consumption never leaks into the mutually
    exclusive sibling arm — the same discipline G002 applies to donation."""
    if not block:
        return False
    last = block[-1]
    if isinstance(last, (ast.Return, ast.Raise, ast.Break, ast.Continue)):
        return True
    if isinstance(last, (ast.With, ast.AsyncWith)):
        return _terminates(last.body)
    if isinstance(last, ast.If):
        return bool(last.orelse) and _terminates(last.body) and _terminates(
            last.orelse)
    return False


class _DonationChecker:
    def __init__(self, analyzer: Analyzer, mod: ModuleInfo, fi: FuncInfo):
        self.an = analyzer
        self.mod = mod
        self.fi = fi
        self.findings: List[Finding] = []
        # name -> (callee description, call lineno)
        self.consumed: Dict[str, Tuple[str, int]] = {}

    def _donating_callee(self, call: ast.Call
                         ) -> Optional[Tuple[str, Optional[Tuple[int, ...]]]]:
        func = call.func
        if isinstance(func, ast.Name):
            f = self.fi
            while f is not None:
                env = self.an.envs.get(f)
                if env and func.id in env.donating_locals:
                    return func.id, env.donating_locals[func.id]
                f = f.parent
            menv = self.an.module_envs.get(self.mod)
            if menv and func.id in menv.donating_locals:
                return func.id, menv.donating_locals[func.id]
            return None
        if isinstance(func, ast.Attribute):
            if func.attr in self.an.index.donating_attrs:
                return (dotted(func) or func.attr,
                        self.an.index.donating_attrs[func.attr])
            return None
        if isinstance(func, ast.Call):
            info = _jit_call_info(self.mod, func)
            if info and info[3]:
                return "jax.jit(...)", info[2]
        return None

    def run(self) -> List[Finding]:
        self._visit_block(_body_of(self.fi))
        return self.findings

    def _visit_block(self, stmts: List[ast.stmt]) -> None:
        for s in stmts:
            self._visit_stmt(s)

    def _store(self, t: ast.expr) -> None:
        if isinstance(t, ast.Name):
            self.consumed.pop(t.id, None)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._store(e)
        elif isinstance(t, ast.Starred):
            self._store(t.value)

    def _visit_stmt(self, s: ast.stmt) -> None:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            return
        if isinstance(s, ast.Assign):
            self._visit_expr(s.value)
            for t in s.targets:
                if isinstance(t, (ast.Name, ast.Tuple, ast.List, ast.Starred)):
                    self._store(t)
                else:
                    self._visit_expr(t)
            return
        if isinstance(s, ast.AugAssign):
            self._visit_expr(s.value)
            if isinstance(s.target, ast.Name):
                self._load(ast.Name(id=s.target.id, ctx=ast.Load(),
                                    lineno=s.lineno, col_offset=s.col_offset))
                self._store(s.target)
            else:
                self._visit_expr(s.target)
            return
        if isinstance(s, (ast.For, ast.AsyncFor)):
            self._visit_expr(s.iter)
            self._store(s.target)
            self._visit_block(s.body)
            self._visit_block(s.orelse)
            return
        if isinstance(s, ast.If):
            self._visit_expr(s.test)
            before = dict(self.consumed)
            self._visit_block(s.body)
            # a branch that terminates (return/raise/...) contributes nothing
            # to the join — code after the If never sees its consumption
            after_body = ({} if _terminates(s.body) else self.consumed)
            self.consumed = dict(before)
            self._visit_block(s.orelse)
            if s.orelse and _terminates(s.orelse):
                self.consumed = dict(before)
            # union: "may be consumed" after the branch join
            merged = dict(self.consumed)
            merged.update(after_body)
            self.consumed = merged
            return
        if isinstance(s, (ast.With, ast.AsyncWith)):
            for item in s.items:
                self._visit_expr(item.context_expr)
                if item.optional_vars is not None:
                    self._store(item.optional_vars)
            self._visit_block(s.body)
            return
        for child in ast.iter_child_nodes(s):
            if isinstance(child, ast.expr):
                self._visit_expr(child)
            elif isinstance(child, ast.stmt):
                self._visit_stmt(child)

    def _load(self, n: ast.Name) -> None:
        hit = self.consumed.get(n.id)
        if hit is not None:
            callee, line = hit
            self.findings.append(_mk(
                self.mod, "G002", n,
                f"`{n.id}` was donated to `{callee}` (line {line}) and is "
                "read again — the buffer is invalidated (use-after-donate)",
            ))

    def _visit_expr(self, e: Optional[ast.expr]) -> None:
        if e is None:
            return
        if isinstance(e, ast.Name) and isinstance(e.ctx, ast.Load):
            self._load(e)
            return
        if isinstance(e, ast.Call):
            self._visit_expr(e.func) if not isinstance(
                e.func, ast.Name) else None
            for a in e.args:
                self._visit_expr(a)
            for k in e.keywords:
                self._visit_expr(k.value)
            don = self._donating_callee(e)
            if don is not None:
                callee, argnums = don
                positions = (range(len(e.args)) if argnums is None
                             else [p for p in argnums if p < len(e.args)])
                for p in positions:
                    a = e.args[p]
                    if isinstance(a, ast.Name):
                        self.consumed[a.id] = (callee, e.lineno)
            return
        if isinstance(e, ast.Lambda):
            return
        for child in ast.iter_child_nodes(e):
            if isinstance(child, ast.expr):
                self._visit_expr(child)


# ---------------------------------------------------------------------------
# G003: recompile hazards (any function)
# ---------------------------------------------------------------------------


def _scalar_arg_repr(mod: ModuleInfo, a: ast.expr) -> Optional[str]:
    """A data-derived Python scalar/shape expression, else None."""
    if isinstance(a, ast.Call):
        ds = dotted(a.func)
        if ds in ("int", "round", "len") and a.args:
            inner = a.args[0]
            if not isinstance(inner, ast.Constant):
                return f"{ds}(...)"
        return None
    if isinstance(a, ast.Attribute) and a.attr == "shape":
        return f"{dotted(a) or '<expr>.shape'}"
    if (isinstance(a, ast.Subscript)
            and isinstance(a.value, ast.Attribute)
            and a.value.attr == "shape"):
        return f"{dotted(a.value) or '<expr>.shape'}[...]"
    return None


class _RecompileChecker:
    def __init__(self, analyzer: Analyzer, mod: ModuleInfo, fi: FuncInfo):
        self.an = analyzer
        self.mod = mod
        self.fi = fi
        self.findings: List[Finding] = []

    def _strictjit_callee(self, call: ast.Call) -> Optional[str]:
        func = call.func
        if isinstance(func, ast.Name):
            f = self.fi
            while f is not None:
                env = self.an.envs.get(f)
                if env and func.id in env.strictjit_locals:
                    return func.id
                f = f.parent
            menv = self.an.module_envs.get(self.mod)
            if menv and func.id in menv.strictjit_locals:
                return func.id
        elif isinstance(func, ast.Attribute):
            if func.attr in self.an.index.strictjit_attrs:
                return dotted(func) or func.attr
        return None

    def run(self) -> List[Finding]:
        from .analyzer import _walk_shallow

        for node in _walk_shallow(self.fi.node):
            if isinstance(node, ast.Call):
                callee = self._strictjit_callee(node)
                if callee is not None:
                    for a in list(node.args) + [k.value
                                                for k in node.keywords]:
                        rep = _scalar_arg_repr(self.mod, a)
                        if rep is not None:
                            self.findings.append(_mk(
                                self.mod, "G003", a,
                                f"data-derived Python scalar `{rep}` fed to "
                                f"jit-compiled `{callee}` without "
                                "static_argnums — every new value recompiles",
                            ))
            elif isinstance(node, ast.DictComp):
                for gen in node.generators:
                    it = gen.iter
                    is_set = (isinstance(it, ast.Set)
                              or (isinstance(it, ast.Call)
                                  and dotted(it.func) == "set"))
                    if is_set:
                        self.findings.append(_mk(
                            self.mod, "G003", node,
                            "dict built by iterating a set feeds pytree "
                            "construction — set order is process-dependent, "
                            "so the pytree structure (and the compiled "
                            "program) changes between runs",
                        ))
        return self.findings


def check_untraced(analyzer: Analyzer, mod: ModuleInfo,
                   fi: FuncInfo) -> List[Finding]:
    out = _DonationChecker(analyzer, mod, fi).run()
    out += _RecompileChecker(analyzer, mod, fi).run()
    return out
