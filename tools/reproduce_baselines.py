"""One-command reproduction of the reference's published accuracy table.

Each row maps a line of the reference's benchmark doc
(``doc/en/simulation/benchmark/BENCHMARK_simulation.md``; hyper-parameters
from its config blocks at lines 16-175) to a run of OUR sp engine with the
same federated config. Staged real data (the same on-disk formats the
reference consumes — ``data/real_readers.py`` + the IDX/pickle readers in
``data/datasets.py``) is picked up automatically from ``--cache-dir``;
without it the run falls back to the synthetic generators and the output
says so — a synthetic run exercises the config, it does NOT reproduce the
published number (this pod has no egress to download the corpora).

Usage:
  python tools/reproduce_baselines.py --list
  python tools/reproduce_baselines.py --row mnist_lr --cache-dir ~/fedml_data
  python tools/reproduce_baselines.py --row stackoverflow_lr \
      --cache-dir tests/fixtures/stackoverflow --rounds 4   # fixture smoke

Prints one JSON line per run:
  {"row", "dataset", "model", "published_acc", "test_acc", "rounds",
   "data": "real"|"synthetic", "reproduces": bool|null}
``reproduces`` compares against the published number minus ``--slack``
(default 2 acc points) and is null for synthetic data.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# BENCHMARK_simulation.md table (lines 3-12) + config blocks (lines 16-175).
# Fields follow the yaml blocks verbatim; published = the "Exp" column.
ROWS = {
    "mnist_lr": dict(
        dataset="mnist", model="lr", published=81.9,
        client_num_in_total=1000, client_num_per_round=10, comm_round=200,
        epochs=1, batch_size=10, learning_rate=0.03, client_optimizer="sgd",
        source="BENCHMARK_simulation.md:5 (config :16-34)",
    ),
    "femnist_cnn": dict(
        dataset="femnist", model="cnn", published=80.2,
        client_num_in_total=10, client_num_per_round=10, comm_round=1000,
        epochs=1, batch_size=20, learning_rate=0.03, client_optimizer="sgd",
        source="BENCHMARK_simulation.md:6 (config :95-115)",
    ),
    "fed_cifar100_resnet18gn": dict(
        dataset="fed_cifar100", model="resnet18_gn", published=34.0,
        client_num_in_total=10, client_num_per_round=10, comm_round=4000,
        epochs=1, batch_size=10, learning_rate=0.1, client_optimizer="sgd",
        source="BENCHMARK_simulation.md:7 (config :119-139)",
    ),
    "shakespeare_rnn": dict(
        dataset="shakespeare", model="rnn", published=53.1,
        client_num_in_total=10, client_num_per_round=10, comm_round=10,
        epochs=1, batch_size=10, learning_rate=0.8, client_optimizer="sgd",
        source="BENCHMARK_simulation.md:8 (config :40-60)",
    ),
    "fed_shakespeare_rnn": dict(
        dataset="fed_shakespeare", model="rnn", published=57.1,
        client_num_in_total=10, client_num_per_round=10, comm_round=1000,
        epochs=1, batch_size=10, learning_rate=0.8, client_optimizer="sgd",
        source="BENCHMARK_simulation.md:9 (config :66-87)",
    ),
    "stackoverflow_lr": dict(
        dataset="stackoverflow_lr", model="lr", published=None,
        client_num_in_total=10, client_num_per_round=10, comm_round=2000,
        epochs=1, batch_size=10, learning_rate=0.03, client_optimizer="sgd",
        source="BENCHMARK_simulation.md:143-163 (no Exp number in table)",
    ),
    "stackoverflow_nwp_rnn": dict(
        dataset="stackoverflow_nwp", model="rnn", published=18.3,
        client_num_in_total=10, client_num_per_round=10, comm_round=2000,
        epochs=1, batch_size=10, learning_rate=0.03, client_optimizer="sgd",
        source="BENCHMARK_simulation.md:10 (config :167-188)",
    ),
}


def run_row(name: str, cache_dir: str, rounds: int | None,
            slack: float) -> dict:
    row = ROWS[name]
    from bench import _maybe_force_platform

    _maybe_force_platform()  # BENCH_PLATFORM=cpu — off-TPU driving
    import fedml_tpu as fedml
    from fedml_tpu import data as data_mod
    from fedml_tpu import models as model_mod
    from fedml_tpu.arguments import Arguments
    from fedml_tpu.runner import FedMLRunner

    overrides = dict(
        dataset=row["dataset"], model=row["model"],
        partition_method="hetero", partition_alpha=0.5,
        federated_optimizer="FedAvg",
        client_num_in_total=row["client_num_in_total"],
        client_num_per_round=row["client_num_per_round"],
        comm_round=rounds if rounds is not None else row["comm_round"],
        epochs=row["epochs"], batch_size=row["batch_size"],
        learning_rate=row["learning_rate"],
        client_optimizer=row["client_optimizer"],
        frequency_of_the_test=10_000, backend="sp",
    )
    if cache_dir:
        overrides["data_cache_dir"] = cache_dir
    args = fedml.init(Arguments(overrides=overrides), should_init_logs=False)
    ds, output_dim = data_mod.load(args)
    # natural partitions define the client count; a fixture-scale corpus
    # may hold fewer clients than the published cohort
    if int(args.client_num_per_round) > ds.client_num:
        args.client_num_per_round = ds.client_num
        args.client_num_in_total = ds.client_num
    # real on-disk data: natural LEAF/TFF partitions or the IDX/pickle
    # readers; anything else is the synthetic fallback
    real_tag = ds.meta.get("real_files")
    real = bool(ds.meta.get("natural_partition") or real_tag)
    # a string tag = real data under a DEVIATING protocol (e.g. the
    # mnist t10k-split when train images can't be staged) — reported, and
    # excluded from an unqualified "reproduces" claim below
    protocol = real_tag if isinstance(real_tag, str) else "published"
    # fixture-scale corpora can carry smaller vocab/tag spaces than the
    # registry's full-staging dims — size the model from the DATA (at full
    # staging these match the registry exactly)
    if ds.task == "tagpred":
        output_dim = int(ds.train_y.shape[-1])
    bundle = model_mod.create(args, output_dim)
    bundle.input_shape = tuple(ds.train_x.shape[2:])
    res = FedMLRunner(args, fedml.get_device(args), ds, bundle).run()
    acc = 100.0 * float(res["test_acc"])
    published = row["published"]
    out = {
        "row": name,
        "dataset": row["dataset"],
        "model": row["model"],
        "published_acc": published,
        "test_acc": round(acc, 2),
        "rounds": overrides["comm_round"],
        "data": "real" if real else "synthetic",
        "protocol": protocol,
        # an unqualified claim needs real data, the full round budget, AND
        # the published protocol; protocol deviations report the accuracy
        # comparison under "reproduces_deviating_protocol" instead
        "reproduces": (
            acc >= published - slack
            if real and published is not None and protocol == "published"
            and overrides["comm_round"] >= row["comm_round"] else None
        ),
        "source": row["source"],
    }
    if real and published is not None and protocol != "published" \
            and overrides["comm_round"] >= row["comm_round"]:
        out["reproduces_deviating_protocol"] = bool(acc >= published - slack)
    print(json.dumps(out))
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--row", choices=sorted(ROWS), action="append")
    ap.add_argument("--all", action="store_true")
    ap.add_argument("--list", action="store_true")
    ap.add_argument("--cache-dir", default="")
    ap.add_argument("--rounds", type=int, default=None,
                    help="override comm_round (smoke runs)")
    ap.add_argument("--slack", type=float, default=2.0)
    ap.add_argument("--platform", default="", choices=["", "cpu"],
                    help="cpu = force the 8-virtual-device CPU platform "
                         "(the JAX_PLATFORMS env var is ignored under the "
                         "axon TPU plugin; jax.config is authoritative)")
    a = ap.parse_args()
    if a.platform == "cpu":
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    if a.list:
        for name, row in ROWS.items():
            print(f"{name:28s} {row['dataset']:18s} {row['model']:12s} "
                  f"published={row['published']}  ({row['source']})")
        return
    names = sorted(ROWS) if a.all else (a.row or [])
    if not names:
        ap.error("pass --row NAME (repeatable), --all, or --list")
    results = [run_row(n, a.cache_dir, a.rounds, a.slack) for n in names]
    bad = [r for r in results if r["reproduces"] is False]
    sys.exit(1 if bad else 0)


if __name__ == "__main__":
    main()
