"""Kernel-level CP-path measurement (VERDICT r3 #6, r4 #3).

A sequence axis of 1-vs-2 on the virtual CPU mesh says nothing about
performance, so this measures what CAN be measured honestly single-chip:

1. The ring-attention INNER engines — fp32 einsum block attend + einsum
   blockwise backward (the r3 path) vs the Pallas splash kernel forward +
   the r5 splash dq/dkv kernel backward — swept over real context-parallel
   block shapes, with a grad-parity check between the two paths.
2. A full CP *train step* (fwd+bwd+AdamW) of the flagship shape at long
   context through ``CheetahTrainer`` with the sequence axis active, plus
   the same step with CP off — the single-chip CP tax, as ``train_step_ms``.

Runs on the one real TPU chip with a 1-device ``sequence`` mesh (the ring
machinery — shard_map, axis_index, ppermute, online merge — is all live;
only the hop count is 1). Writes RING_KERNEL_BENCH.json.

Usage:  python tools/bench_ring_kernel.py [--blocks 2048,4096,8192]
        python tools/bench_ring_kernel.py --smoke   # CPU plumbing check
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _sync(x):
    import numpy as np

    import jax

    return float(np.asarray(jax.tree.leaves(x)[0]).ravel()[0])


def measure_inner(B, Lb, H, D, steps, interpret=False) -> dict:
    """Einsum vs kernel inner engines at one block shape + grad parity."""
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from fedml_tpu.parallel.ring_attention import make_ring_attention
    from fedml_tpu.parallel.sharding import compat_shard_map

    mesh = Mesh(np.asarray(jax.devices()[:1]), axis_names=("sequence",))
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.standard_normal((B, Lb, H, D)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((B, Lb, H, D)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((B, Lb, H, D)), jnp.bfloat16)

    def one(use_kernel: bool):
        ring = make_ring_attention(1, "sequence", use_kernel=use_kernel,
                                   interpret=interpret)
        spec = P(None, "sequence", None, None)
        sm = compat_shard_map(ring, mesh=mesh, in_specs=(spec,) * 3,
                              out_specs=spec)

        @jax.jit
        def fwd(q, k, v):
            return jnp.sum(sm(q, k, v).astype(jnp.float32) ** 2)

        @jax.jit
        def fwd_bwd(q, k, v):
            return jax.value_and_grad(
                lambda q, k, v: jnp.sum(sm(q, k, v).astype(jnp.float32) ** 2),
                argnums=(0, 1, 2),
            )(q, k, v)

        def timeit(f):
            r = f(q, k, v)
            _sync(r)
            t0 = time.perf_counter()
            for _ in range(steps):
                r = f(q, k, v)
            _sync(r)
            return (time.perf_counter() - t0) / steps, r

        dt_f, _ = timeit(fwd)
        dt_fb, (l, grads) = timeit(fwd_bwd)
        return {"ms_per_fwd": round(dt_f * 1e3, 2),
                "ms_per_fwd_bwd": round(dt_fb * 1e3, 2),
                "loss": float(l)}, grads

    einsum, g_e = one(False)
    kernel, g_k = one(True)

    import numpy as np

    def rel_l2(a, b):
        a = np.asarray(a, np.float32).ravel()
        b = np.asarray(b, np.float32).ravel()
        return float(np.linalg.norm(a - b) / max(np.linalg.norm(b), 1e-9))

    return {
        "einsum_inner": einsum,
        "kernel_inner": kernel,
        "kernel_fwd_speedup": round(
            einsum["ms_per_fwd"] / kernel["ms_per_fwd"], 2
        ),
        "kernel_fwd_bwd_speedup": round(
            einsum["ms_per_fwd_bwd"] / kernel["ms_per_fwd_bwd"], 2
        ),
        # bf16 inputs: agreement to ~1e-2 rel-L2 is bit-level-reasonable;
        # the exact check is tests/test_ring_attention.py (fp32, interpret)
        "grad_rel_l2": {
            n: rel_l2(a, b) for n, a, b in
            (("dq", g_k[0], g_e[0]), ("dk", g_k[1], g_e[1]),
             ("dv", g_k[2], g_e[2]))
        },
        "loss_rel_diff": abs(einsum["loss"] - kernel["loss"])
        / max(abs(einsum["loss"]), 1e-9),
    }


def measure_train_step(seq, batch, steps, smoke=False) -> dict:
    """Full CP train step (fwd+bwd+update) vs the same step with CP off."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from fedml_tpu.parallel.sharding import make_mesh
    from fedml_tpu.parallel.train_step import CheetahTrainer, make_optimizer
    from fedml_tpu.parallel.transformer import TransformerConfig

    if smoke:
        base = dict(vocab_size=512, d_model=128, n_layers=2, n_heads=4,
                    n_kv_heads=2, d_ff=384, max_seq_len=seq)
    else:
        # the bench.py flagship body at long context (attn blocks clamped
        # to the measured (512, 512))
        base = dict(vocab_size=32000, d_model=2048, n_layers=8, n_heads=16,
                    n_kv_heads=4, d_ff=5632, max_seq_len=seq,
                    attn_block_q=512, attn_block_kv=512)
    rng = np.random.RandomState(0)
    tok = jnp.asarray(rng.randint(0, base["vocab_size"], (batch, seq))
                      .astype(np.int32))
    mask = jnp.ones((batch, seq), jnp.int32)

    def one(seq_sharded: bool):
        mesh = make_mesh({"sequence": 1}, devices=jax.devices()[:1])
        last = None
        for rung in (dict(remat=False), dict(remat=True, remat_policy="full")):
            cfg = TransformerConfig(**{**base, **rung})
            tr = CheetahTrainer(
                cfg, mesh,
                optimizer=make_optimizer(learning_rate=3e-4, warmup_steps=5,
                                         total_steps=100,
                                         mu_dtype=jnp.bfloat16),
                seq_sharded=seq_sharded,
            )
            try:
                state = tr.init_state(jax.random.PRNGKey(0))
                state, m = tr.train_step(state, tok, mask)
                _sync(m["loss"])
            except Exception as e:
                last = f"{type(e).__name__}: {e}"[:300]
                state = tr = None
                continue
            break
        if state is None:
            return {"error": last}
        n_params = sum(int(p.size) for p in jax.tree.leaves(state.params))
        for _ in range(2):
            state, m = tr.train_step(state, tok, mask)
        _sync(m["loss"])
        t0 = time.perf_counter()
        for _ in range(steps):
            state, m = tr.train_step(state, tok, mask)
        _sync(m["loss"])
        dt = (time.perf_counter() - t0) / steps
        tok_s = batch * seq / dt
        res = {"train_step_ms": round(dt * 1e3, 1),
               "tokens_per_sec": round(tok_s),
               "remat": cfg.remat_policy if cfg.remat else "none",
               "loss": round(float(m["loss"]), 4)}
        from bench import TPU_PEAK_FLOPS

        peak = TPU_PEAK_FLOPS.get(jax.devices()[0].device_kind)
        if peak:
            fpt = 6.0 * n_params + 12.0 * seq * cfg.n_layers * cfg.d_model
            res["mfu"] = round(tok_s * fpt / peak, 4)
        return res

    cp = one(True)
    no_cp = one(False)
    out = {"seq": seq, "batch": batch, "cp_on": cp, "cp_off": no_cp}
    if "train_step_ms" in cp and "train_step_ms" in no_cp:
        out["cp_tax"] = round(
            cp["train_step_ms"] / no_cp["train_step_ms"], 3
        )
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--blocks", default="2048,4096,8192")
    ap.add_argument("--heads", type=int, default=16)
    ap.add_argument("--head-dim", type=int, default=128)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--train-seq", type=int, default=4096)
    ap.add_argument("--train-batch", type=int, default=4)
    ap.add_argument("--smoke", action="store_true",
                    help="CPU plumbing check: tiny shapes, interpret kernels")
    ap.add_argument("--out", default=os.path.join(REPO,
                                                  "RING_KERNEL_BENCH.json"))
    a = ap.parse_args()

    from bench import _maybe_force_platform

    _maybe_force_platform()
    import jax

    on_tpu = jax.devices()[0].platform == "tpu"
    if not on_tpu and not a.smoke:
        print(json.dumps({"skipped": "not a tpu host"}))
        return

    if a.smoke:
        blocks, B, H, D, steps = [256], 1, 2, 128, 2
        tseq, tbatch = 128, 2
    else:
        blocks = [int(x) for x in a.blocks.split(",") if x]
        B, H, D, steps = a.batch, a.heads, a.head_dim, a.steps
        tseq, tbatch = a.train_seq, a.train_batch

    out = {
        "shape": {"batch": B, "heads": H, "head_dim": D},
        "blocks": {},
        "device": jax.devices()[0].device_kind,
        "smoke": bool(a.smoke),
    }
    for Lb in blocks:
        out["blocks"][str(Lb)] = measure_inner(
            B, Lb, H, D, steps, interpret=a.smoke and not on_tpu
        )
        print(f"block {Lb}: {json.dumps(out['blocks'][str(Lb)])}",
              file=sys.stderr, flush=True)
    out["train_step"] = measure_train_step(tseq, tbatch, max(steps // 2, 2),
                                           smoke=a.smoke)
    print(json.dumps(out))
    if not a.smoke:
        with open(a.out, "w") as f:
            json.dump(out, f, indent=2)


if __name__ == "__main__":
    main()
