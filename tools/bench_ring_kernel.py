"""Kernel-level CP-path measurement (VERDICT r3 next #6).

A sequence axis of 1-vs-2 on the virtual CPU mesh says nothing about
performance, so this measures what CAN be measured honestly single-chip:
the ring-attention INNER engine — fp32 einsum block attend (the r3 path)
vs the Pallas flash kernel merge (the r4 path) — at real context-parallel
block shapes, fwd+bwd through the shared custom-VJP blockwise backward.

Runs on the one real TPU chip with a 1-device ``sequence`` mesh (the ring
machinery — shard_map, axis_index, ppermute, online merge — is all live;
only the hop count is 1). Writes RING_KERNEL_BENCH.json.

Usage:  python tools/bench_ring_kernel.py [--batch 4] [--block 2048]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--block", type=int, default=2048)
    ap.add_argument("--heads", type=int, default=16)
    ap.add_argument("--head-dim", type=int, default=128)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--out", default=os.path.join(REPO,
                                                  "RING_KERNEL_BENCH.json"))
    a = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from fedml_tpu.parallel.ring_attention import make_ring_attention
    from fedml_tpu.parallel.sharding import compat_shard_map

    if jax.devices()[0].platform != "tpu":
        print(json.dumps({"skipped": "not a tpu host"}))
        return

    B, Lb, H, D = a.batch, a.block, a.heads, a.head_dim
    mesh = Mesh(np.asarray(jax.devices()[:1]), axis_names=("sequence",))
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.standard_normal((B, Lb, H, D)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((B, Lb, H, D)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((B, Lb, H, D)), jnp.bfloat16)

    def measure(use_kernel: bool) -> dict:
        ring = make_ring_attention(1, "sequence", use_kernel=use_kernel)
        spec = P(None, "sequence", None, None)
        sm = compat_shard_map(ring, mesh=mesh, in_specs=(spec,) * 3,
                              out_specs=spec)

        @jax.jit
        def fwd(q, k, v):
            return jnp.sum(sm(q, k, v).astype(jnp.float32) ** 2)

        @jax.jit
        def fwd_bwd(q, k, v):
            l, grads = jax.value_and_grad(
                lambda q, k, v: jnp.sum(sm(q, k, v).astype(jnp.float32) ** 2),
                argnums=(0, 1, 2),
            )(q, k, v)
            return l, grads

        def sync(x):
            return float(np.asarray(jax.tree.leaves(x)[0]).ravel()[0])

        def timeit(f):
            r = f(q, k, v)
            sync(r)
            t0 = time.perf_counter()
            for _ in range(a.steps):
                r = f(q, k, v)
            sync(r)
            return (time.perf_counter() - t0) / a.steps, r

        dt_f, _ = timeit(fwd)
        dt_fb, (l, _) = timeit(fwd_bwd)
        return {"ms_per_fwd": round(dt_f * 1e3, 2),
                "ms_per_fwd_bwd": round(dt_fb * 1e3, 2), "loss": float(l)}

    einsum = measure(False)
    kernel = measure(True)
    out = {
        "shape": {"batch": B, "block": Lb, "heads": H, "head_dim": D},
        "einsum_inner": einsum,
        "flash_kernel_inner": kernel,
        "kernel_fwd_speedup": round(
            einsum["ms_per_fwd"] / kernel["ms_per_fwd"], 2
        ),
        "kernel_fwd_bwd_speedup": round(
            einsum["ms_per_fwd_bwd"] / kernel["ms_per_fwd_bwd"], 2
        ),
        # both paths share the blockwise custom-VJP backward; the numbers
        # differ by the forward engine (+ what XLA can fuse around it)
        "loss_rel_diff": abs(einsum["loss"] - kernel["loss"])
        / max(abs(einsum["loss"]), 1e-9),
        "device": jax.devices()[0].device_kind,
    }
    print(json.dumps(out))
    with open(a.out, "w") as f:
        json.dump(out, f, indent=2)


if __name__ == "__main__":
    main()
