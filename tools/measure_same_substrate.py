"""Same-substrate baseline: BOTH stacks measured on CPU, one tool, one config.

VERDICT r2 weak #3: ``vs_baseline`` divides a TPU number by the reference's
torch-CPU number, conflating hardware with architecture. This tool measures
the fedml_tpu sp engine AND the reference's FedAvgAPI on the SAME substrate
(CPU), the same federation config as ``tools/measure_ref_baseline.py``
(100 clients, 10/round, 500 samples/client, batch 32, 1 epoch), and writes
both numbers plus their ratio to ``SELF_CPU_BASELINE.json``; ``bench.py``
reports the ratio as ``vs_baseline_same_substrate``.

Model notes: the default legs are LR (where Python overhead is largest),
the fed_shakespeare RNN (mid-size LSTM), and the FEMNIST CNN — the ratio
is reported per leg because it tracks backend kernel quality, not just
architecture (VERDICT r3 weak #4). ResNet-56 stays opt-in because
XLA:CPU's single-threaded LLVM backend takes >60 minutes to compile the
vmapped ResNet-56 fwd+bwd on this host (measured twice; the run never
completed). The federation shape is held CONSTANT across legs so they
differ only by model.

Usage:  python tools/measure_same_substrate.py [--rounds 3] [--models lr,rnn,cnn]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_TOTAL, PER_ROUND, PER_CLIENT, BATCH = 100, 10, 500, 32

# per-leg model wiring: (our dataset/model names, input shape, classes).
# cnn note: conv models on CPU run the r5 lax.map cohort (the vmapped
# grouped-conv lowering and its >60-min compiles are gone), but plain
# XLA:CPU conv codegen still executes small convs ~100x slower than
# torch's oneDNN kernels — an execution-backend artifact of the CPU
# comparison substrate, not architecture (the identical program on TPU is
# bench.py's headline); the leg is reported with that caveat. rnn is the
# mid-size leg free of the conv story (LSTM: oneDNN ~2x).
MODELS = {
    "lr": dict(dataset="mnist", shape=(28, 28, 1), classes=10),
    "rnn": dict(dataset="shakespeare", shape=(80,), classes=90),
    "cnn": dict(dataset="femnist", shape=(28, 28, 1), classes=62),
    "resnet56": dict(dataset="cifar10", shape=(32, 32, 3), classes=10),
}


def measure_ours(model: str, rounds: int) -> float:
    import jax

    jax.config.update("jax_platforms", "cpu")
    # persistent compile cache: XLA:CPU compiles of conv models take tens
    # of minutes on this one-core host; pay once (same dir as conftest)
    cache = os.environ.get("JAX_COMPILATION_CACHE_DIR",
                           "/tmp/fedml_tpu_jax_cache")
    os.makedirs(cache, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache)

    import numpy as np

    import fedml_tpu as fedml
    from fedml_tpu import models as model_mod
    from fedml_tpu.arguments import Arguments
    from fedml_tpu.data.fed_dataset import FedDataset, pad_cap_to_batch_multiple
    from fedml_tpu.simulation.sp_api import FedAvgAPI

    m = MODELS[model]
    args = fedml.init(Arguments(overrides=dict(
        dataset=m["dataset"], model=model,
        client_num_in_total=N_TOTAL, client_num_per_round=PER_ROUND,
        comm_round=rounds + 1, epochs=1, batch_size=BATCH,
        learning_rate=0.1, frequency_of_the_test=1000,
    )), should_init_logs=False)
    # build the federation EXPLICITLY at the reference's exact workload
    # (PER_CLIENT samples per client — the registry's per-client default for
    # mnist is 60 and would understate the work by ~8x)
    shape, classes = m["shape"], m["classes"]
    rng = np.random.RandomState(0)
    if model == "rnn":  # char-LM: int token windows, next-token targets
        x = rng.randint(1, classes, (N_TOTAL, PER_CLIENT) + shape)
        x = x.astype(np.int32)
        y = np.zeros_like(x)
        y[..., :-1] = x[..., 1:]
        task = "nwp"
    else:
        x = rng.randn(N_TOTAL, PER_CLIENT, *shape).astype(np.float32)
        y = rng.randint(0, classes, (N_TOTAL, PER_CLIENT)).astype(np.int32)
        task = "classification"
    ds = FedDataset(
        train_x=x, train_y=y,
        train_counts=np.full((N_TOTAL,), PER_CLIENT, np.int32),
        test_x=x[0, :64], test_y=y[0, :64], class_num=classes, task=task,
    )
    ds = pad_cap_to_batch_multiple(ds, BATCH)
    bundle = model_mod.create(args, classes)
    api = FedAvgAPI(args, fedml.get_device(args), ds, bundle)

    api._train_round(0)  # warmup round (compile)
    jax.tree.leaves(api.global_params)[0].block_until_ready()
    t0 = time.perf_counter()
    for r in range(1, rounds + 1):
        api._train_round(r)
    jax.tree.leaves(api.global_params)[0].block_until_ready()
    return rounds / (time.perf_counter() - t0)


def measure_reference(model: str, rounds: int) -> float:
    """The reference's own loop, via measure_ref_baseline's stub importer."""
    import importlib.util
    import logging

    spec = importlib.util.spec_from_file_location(
        "measure_ref_baseline",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "measure_ref_baseline.py"),
    )
    mrb = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mrb)
    sys.path.insert(0, mrb.REF)
    logging.disable(logging.INFO)
    mrb._import_with_stubs("fedml")

    import numpy as np
    import torch
    from fedml.simulation.sp.fedavg.fedavg_api import FedAvgAPI

    torch.manual_seed(0)
    classes = MODELS[model]["classes"]
    if model == "lr":
        ref_model = torch.nn.Sequential(
            torch.nn.Flatten(), torch.nn.Linear(784, 10)
        )
        shape = (1, 28, 28)
    elif model == "rnn":
        # the reference's shipped fed_shakespeare char-LM
        # (model_hub.py routes fed_shakespeare+rnn here) — per-position
        # forward, same work as our nwp engine; NWP trainer selected via
        # args.dataset below
        from fedml.model.nlp.rnn import RNN_FedShakespeare

        ref_model = RNN_FedShakespeare()
        shape = (80,)
    elif model == "cnn":
        # the reference's FEMNIST CNN (model_hub.py routes femnist+cnn
        # here); its forward unsqueezes the channel dim itself, so the
        # loader feeds unbatched [28, 28] images (cnn.py:60)
        from fedml.model.cv.cnn import CNN_DropOut

        ref_model = CNN_DropOut(only_digits=False)
        shape = (28, 28)
    else:
        from fedml.model.cv.resnet import resnet56

        ref_model = resnet56(class_num=10)
        shape = (3, 32, 32)

    def loader(n, seed):
        g = torch.Generator().manual_seed(seed)
        if model == "rnn":
            x = torch.randint(1, classes, (n,) + shape, generator=g)
            y = torch.zeros((n,) + shape, dtype=torch.long)
            y[..., :-1] = x[..., 1:]
        else:
            x = torch.randn((n,) + shape, generator=g)
            y = torch.randint(0, classes, (n,), generator=g)
        return torch.utils.data.DataLoader(
            torch.utils.data.TensorDataset(x, y), batch_size=BATCH,
            shuffle=False,
        )

    train_local = {i: loader(PER_CLIENT, i) for i in range(N_TOTAL)}
    test_local = {i: loader(8, 10_000 + i) for i in range(N_TOTAL)}
    train_num = {i: PER_CLIENT for i in range(N_TOTAL)}
    dataset = [N_TOTAL * PER_CLIENT, N_TOTAL * 8, None, None,
               train_num, train_local, test_local, 10]
    ref_args = argparse.Namespace(
        # "fed_shakespeare" routes the reference to its NWP trainer
        # (trainer_creator.py:9); any other name gets the CLS trainer
        dataset="fed_shakespeare" if model == "rnn" else "same-substrate",
        model=model, client_num_in_total=N_TOTAL,
        client_num_per_round=PER_ROUND, comm_round=1, epochs=1,
        batch_size=BATCH, learning_rate=0.1, client_optimizer="sgd",
        weight_decay=0.0, frequency_of_the_test=100_000, enable_wandb=False,
    )
    api = FedAvgAPI(ref_args, torch.device("cpu"), dataset, ref_model)
    api._local_test_on_all_clients = lambda *_a, **_k: None
    api.train()  # warmup round
    ref_args.comm_round = rounds
    t0 = time.perf_counter()
    api.train()
    return rounds / (time.perf_counter() - t0)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--models", default="lr,rnn,cnn",
                    help="comma list from " + ",".join(MODELS))
    ap.add_argument("--out",
                    default=os.path.join(REPO, "SELF_CPU_BASELINE.json"))
    a = ap.parse_args()

    legs = {}
    for model in a.models.split(","):
        model = model.strip()
        if model not in MODELS:
            raise SystemExit(f"unknown model {model!r}; known: {list(MODELS)}")
        ours = measure_ours(model, a.rounds)
        ref = measure_reference(model, a.rounds)
        legs[model] = {
            "self_cpu_rounds_per_sec": round(ours, 5),
            "ref_cpu_rounds_per_sec": round(ref, 5),
            "same_substrate_ratio": round(ours / ref, 2),
        }
        print(json.dumps({model: legs[model]}))
    # headline = the lr leg when measured (the apples-to-apples Python-
    # overhead comparison), else the first requested leg — and say which
    headline_leg = "lr" if "lr" in legs else next(iter(legs))
    out = {
        # back-compat top-level keys = the headline leg (bench.py reads these)
        **legs[headline_leg],
        "headline_leg": headline_leg,
        "legs": legs,
        "rounds": a.rounds,
        "config": f"{N_TOTAL}c/{PER_ROUND}pr/{PER_CLIENT}spc/bs{BATCH}/1ep "
                  f"[{a.models}], BOTH stacks on this host's CPU. "
                  "READ THE LEGS TOGETHER: the ratio is kernel-quality-"
                  "dependent, not purely architectural — the fused "
                  "vmap/scan engine wins where per-client Python overhead "
                  "dominates (lr), while for LSTM/conv models torch's "
                  "oneDNN CPU kernels beat plain XLA:CPU codegen (the r5 "
                  "lax.map cohort removed the old vmapped grouped-conv "
                  "compile wall — 224px federated detection now runs on "
                  "CPU — but not the per-kernel quality gap on tiny "
                  "convs). On the TARGET substrate (TPU) the same "
                  "programs are bench.py's headline numbers.",
    }
    with open(a.out, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
