"""Measure OUR sp FedAvg engine on CPU — the same substrate as the reference.

VERDICT r2 weak #3: ``vs_baseline`` divides a TPU number by the reference's
torch-CPU number, conflating hardware with architecture. This tool runs the
fedml_tpu sp engine on the CPU backend in ``tools/measure_ref_baseline.py``'s
EXACT config (100 clients, 10/round, 500 samples/client, batch 32, 1 epoch,
ResNet-56, CIFAR-shaped synthetic) and writes ``SELF_CPU_BASELINE.json``;
``bench.py`` then emits ``vs_baseline_same_substrate`` =
(ours on CPU) / (reference on CPU), isolating the architectural win
(one fused vmap/scan XLA program vs per-client torch loops) from the chip.

Usage:  python tools/measure_same_substrate.py [--rounds 3]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--out",
                    default=os.path.join(REPO, "SELF_CPU_BASELINE.json"))
    a = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")

    import fedml_tpu as fedml
    from fedml_tpu import data as data_mod, models as model_mod
    from fedml_tpu.arguments import Arguments
    from fedml_tpu.simulation.sp_api import FedAvgAPI

    # EXACT measure_ref_baseline.py config (100c/10pr/500spc/bs32/1ep)
    args = fedml.init(Arguments(overrides=dict(
        dataset="cifar10", model="resnet56", client_num_in_total=100,
        client_num_per_round=10, comm_round=a.rounds + 1, epochs=1,
        batch_size=32, learning_rate=0.1, frequency_of_the_test=1000,
    )), should_init_logs=False)
    ds, output_dim = data_mod.load(args)
    bundle = model_mod.create(args, output_dim)
    api = FedAvgAPI(args, fedml.get_device(args), ds, bundle)

    # warmup round (compile)
    api._train_round(0)
    jax.tree.leaves(api.global_params)[0].block_until_ready()

    t0 = time.perf_counter()
    for r in range(1, a.rounds + 1):
        api._train_round(r)
    jax.tree.leaves(api.global_params)[0].block_until_ready()
    dt = time.perf_counter() - t0

    out = {
        "self_cpu_rounds_per_sec": round(a.rounds / dt, 5),
        "rounds": a.rounds,
        "secs": round(dt, 2),
        "config": "100c/10pr/500spc/bs32/1ep resnet56 cifar10-shaped, "
                  "fedml_tpu sp engine on XLA CPU",
    }
    with open(a.out, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
