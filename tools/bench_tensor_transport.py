"""Measure the TRPC-role direct-tensor transport vs the npz path (r4 #10).

Three legs, host-only (no jax):
1. codec: Message.serialize/deserialize with npz vs raw frames;
2. localhost gRPC: unary npz vs streamed raw for a large tensor
   (the reference's trpc benchmark analog, ``python/tests/grpc_benchmark``);
3. decode-aliasing proof: raw decode is zero-copy (views share the buffer).

Writes TENSOR_TRANSPORT_BENCH.json.

Usage: python tools/bench_tensor_transport.py [--mb 256] [--repeats 3]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np


def bench_codec(arrays, repeats) -> dict:
    from fedml_tpu.core.distributed.message import Message

    out = {}
    for fmt in ("npz", "raw"):
        msg = Message("bench", 1, 2)
        msg.set_arrays(arrays)
        msg.wire_format = fmt
        enc = dec = 1e9
        for _ in range(repeats):
            t0 = time.perf_counter()
            payload = msg.serialize()
            enc = min(enc, time.perf_counter() - t0)
            t0 = time.perf_counter()
            back = Message.deserialize(payload)
            dec = min(dec, time.perf_counter() - t0)
        assert all(
            np.array_equal(a, b) for a, b in zip(arrays, back.get_arrays())
        )
        out[fmt] = {"encode_s": round(enc, 4), "decode_s": round(dec, 4),
                    "bytes": len(payload)}
    out["decode_speedup"] = round(
        out["npz"]["decode_s"] / max(out["raw"]["decode_s"], 1e-9), 1
    )
    out["encode_speedup"] = round(
        out["npz"]["encode_s"] / max(out["raw"]["encode_s"], 1e-9), 1
    )
    return out


def bench_grpc(arrays, repeats, base_port=29760) -> dict:
    from fedml_tpu.core.distributed.grpc_backend import GRPCCommManager
    from fedml_tpu.core.distributed.message import Message

    out = {}
    for fmt, port_off in (("npz", 0), ("raw", 4)):
        recv = GRPCCommManager("127.0.0.1", base_port + port_off + 2, rank=2,
                               world_size=3, base_port=base_port + port_off,
                               wire_format=fmt)
        send = GRPCCommManager("127.0.0.1", base_port + port_off + 1, rank=1,
                               world_size=3, base_port=base_port + port_off,
                               wire_format=fmt)
        try:
            msg = Message("bench", 1, 2)
            msg.set_arrays(arrays)
            best = 1e9
            for _ in range(repeats):
                t0 = time.perf_counter()
                send.send_message(msg)
                raw = recv._queue.get(timeout=60)
                back = Message.deserialize(raw)
                best = min(best, time.perf_counter() - t0)
            assert np.array_equal(back.get_arrays()[0], arrays[0])
            nbytes = sum(a.nbytes for a in arrays)
            out[fmt] = {
                "roundtrip_s": round(best, 4),
                "gbps": round(nbytes * 8 / best / 1e9, 2),
                "path": "stream" if fmt == "raw" else "unary",
            }
        finally:
            send.stop_receive_message()
            recv.stop_receive_message()
    out["speedup"] = round(
        out["npz"]["roundtrip_s"] / max(out["raw"]["roundtrip_s"], 1e-9), 2
    )
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mb", type=int, default=256)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--out", default=os.path.join(
        REPO, "TENSOR_TRANSPORT_BENCH.json"))
    a = ap.parse_args()

    rng = np.random.RandomState(0)
    n = a.mb * 1024 * 1024 // 4
    arrays = [rng.standard_normal(n).astype(np.float32)]

    from fedml_tpu.core.distributed.tensor_transport import (
        decode_frames, encode_frames,
    )

    body = encode_frames(arrays)
    views = decode_frames(body)
    zero_copy = not views[0].flags["OWNDATA"]

    res = {
        "payload_mb": a.mb,
        "codec": bench_codec(arrays, a.repeats),
        "grpc_localhost": bench_grpc(arrays, a.repeats),
        "raw_decode_zero_copy": bool(zero_copy),
    }
    print(json.dumps(res))
    with open(a.out, "w") as f:
        json.dump(res, f, indent=2)


if __name__ == "__main__":
    main()
