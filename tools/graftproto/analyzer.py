"""graftproto entry: scan → model → rules → pragma filter.

Mirrors :func:`tools.graftlint.analyzer.analyze_paths`, with graftproto's
own pragma marker (``# graftproto: disable=P006``) and baseline file
(``tools/graftproto/baseline.json``).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

from ..graftlint.analyzer import collect_files, load_modules
from ..graftlint.baseline import find_repo_root
from ..graftlint.pragmas import is_suppressed, parse_pragmas
from .findings import Finding
from .locks import check_locks
from .model import ProtoModel, build_model
from .rules import check_protocol

PRAGMA_TOOL = "graftproto"
DEFAULT_BASELINE_RELPATH = os.path.join("tools", "graftproto",
                                        "baseline.json")


def default_baseline_path(repo_root: str) -> str:
    return os.path.join(repo_root, DEFAULT_BASELINE_RELPATH)


def analyze_paths_with_model(
    paths: Sequence[str], repo_root: Optional[str] = None
) -> Tuple[List[Finding], ProtoModel]:
    """Analyze files/dirs → (pragma-filtered findings, protocol model).

    The model rides along so callers (the coverage gate, ``--json``) can
    inspect the flow-graph classification behind the findings. The baseline
    is NOT applied here — that's the CLI/caller's job, like graftlint.
    """
    if repo_root is None:
        repo_root = find_repo_root(paths[0] if paths else os.getcwd())
    files = collect_files(paths)
    modules = load_modules(files, repo_root)
    model = build_model(modules)
    findings = check_protocol(model, modules) + check_locks(modules)

    out: List[Finding] = []
    pragma_cache: Dict[str, Dict] = {}
    mods_by_rel = {m.rel: m for m in modules.values()}
    for f in findings:
        mod = mods_by_rel.get(f.path)
        if mod is not None:
            pragmas = pragma_cache.setdefault(
                f.path, parse_pragmas(mod.source, tool=PRAGMA_TOOL))
            if is_suppressed(pragmas, f.rule, f.line):
                continue
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out, model


def analyze_paths(paths: Sequence[str],
                  repo_root: Optional[str] = None) -> List[Finding]:
    return analyze_paths_with_model(paths, repo_root)[0]
