"""P008/P009 — lock-order and blocking-call analysis.

Extends graftlint's G005 thread analysis from *data* races to *lock* races:

- build the lock-acquisition graph: a node per lock identity (``(Class,
  attr)`` for ``with self._lock`` / class-attribute locks, ``(module,
  name)`` for module-level locks), an edge A→B whenever B is acquired —
  lexically, or inside any function reached through resolvable calls —
  while A is held;
- **P008**: edges inside a cyclic strongly-connected component (the classic
  A→B / B→A inversion between the comm thread and the trainer), including
  self-edges (re-acquiring a non-reentrant ``threading.Lock``);
- **P009**: blocking calls while holding a lock — zero-arg ``join()`` /
  ``get()`` / ``wait()``, ``recv``/``accept``/``select``, ``os.fsync``
  (the ledger-commit stall), ``time.sleep`` and Orbax
  ``wait_until_finished`` — directly or through a resolvable callee.

Resolution is deliberately conservative: intra-class ``self.m()`` calls,
module-level functions, module-qualified ``alias.fn()`` calls, and a
class-hierarchy match on distinctive method names (graftlint's CHA with its
stoplist).

Bare ``lock.acquire()`` / ``lock.release()`` pairs are tracked too, in
document order within one function: the lock counts as held from the
``acquire()`` statement to the matching ``release()`` (or the end of the
function — an acquire that escapes is treated as still held, which is what
makes it visible to callers through ``own_locks``). This catches the
``acquire(); try: ... finally: release()`` idiom the ``with``-only model
was blind to. Only the zero-argument form counts: a conditional
``acquire(blocking=False)`` / ``acquire(timeout=...)`` may fail to take
the lock, so treating it as held would fabricate edges.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..graftlint.analyzer import CHA_STOPLIST, FuncInfo, ModuleInfo, dotted
from ..graftlint.threads import _is_lock_expr
from .findings import Finding
from .model import owning_class

LockId = Tuple[str, str]  # (scope: class or module, attr/name)

# extra method names too generic for CHA here, on top of graftlint's list
PROTO_CHA_STOPLIST = CHA_STOPLIST | {
    "cancel", "set", "is_set", "serialize", "deserialize", "encode",
    "decode", "train", "evaluate",
}

# blocking when called with NO args and NO timeout kwarg
BLOCKING_IF_UNTIMED = {"join", "get", "wait"}
# always blocking
BLOCKING_ALWAYS = {"fsync", "sleep", "recv", "recv_into", "accept",
                   "select", "wait_until_finished"}


class _FnFacts:
    __slots__ = ("fi", "mod", "own_locks", "direct_edges", "direct_blocks",
                 "calls", "trans_locks", "trans_blocks")

    def __init__(self, fi: FuncInfo, mod: ModuleInfo):
        self.fi = fi
        self.mod = mod
        self.own_locks: Set[LockId] = set()
        # (held, acquired, line)
        self.direct_edges: List[Tuple[LockId, LockId, int]] = []
        # (description, line, held lock)
        self.direct_blocks: List[Tuple[str, int, LockId]] = []
        # (callee key, line, held locks at the call)
        self.calls: List[Tuple[int, int, Tuple[LockId, ...]]] = []
        self.trans_locks: Set[LockId] = set()
        self.trans_blocks: List[Tuple[str, str]] = []  # (desc, "rel:line")


def check_locks(modules: Dict[str, ModuleInfo]) -> List[Finding]:
    facts: Dict[int, _FnFacts] = {}
    all_methods: Dict[str, List[FuncInfo]] = {}
    for mod in modules.values():
        for methods in mod.classes.values():
            for m in methods.values():
                all_methods.setdefault(m.name, []).append(m)

    for mod in modules.values():
        for fi in mod.funcs_by_node.values():
            facts[id(fi.node)] = _FnFacts(fi, mod)
    for f in facts.values():
        _scan_function(f, modules, all_methods)
    _fixpoint(facts)
    return _emit(facts)


# ---------------------------------------------------------------------------
# lock identity + call resolution
# ---------------------------------------------------------------------------


def _lock_id(expr: ast.expr, mod: ModuleInfo,
             fi: FuncInfo) -> Optional[LockId]:
    ds = dotted(expr)
    if not _is_lock_expr(ds, set()):
        return None
    parts = ds.split(".")
    if len(parts) == 1:
        return (mod.name, parts[0])
    base, attr = parts[0], parts[-1]
    if base in ("self", "cls"):
        cls = owning_class(fi)
        return (cls or mod.name, attr)
    if base in mod.classes:
        return (base, attr)
    tgt = mod.imports.get(base)
    if tgt is None and base in mod.from_imports:
        b, orig = mod.from_imports[base]
        tgt = f"{b}.{orig}" if b else orig
    if tgt is not None:
        return (tgt, attr)
    return (f"{mod.name}.{base}", attr)


def _resolve_callees(call: ast.Call, mod: ModuleInfo, fi: FuncInfo,
                     modules: Dict[str, ModuleInfo],
                     all_methods: Dict[str, List[FuncInfo]]
                     ) -> List[FuncInfo]:
    func = call.func
    if isinstance(func, ast.Name):
        target = mod.toplevel.get(func.id)
        if target is not None:
            return [target]
        imp = mod.from_imports.get(func.id)
        if imp:
            target_mod = modules.get(imp[0])
            if target_mod and imp[1] in target_mod.toplevel:
                return [target_mod.toplevel[imp[1]]]
        return []
    if not isinstance(func, ast.Attribute):
        return []
    name = func.attr
    base = func.value
    if isinstance(base, ast.Name):
        if base.id in ("self", "cls"):
            cls = owning_class(fi)
            if cls:
                m = mod.classes.get(cls, {}).get(name)
                if m is not None:
                    return [m]
        tgt = mod.imports.get(base.id)
        if tgt is None and base.id in mod.from_imports:
            # ``from ..mlops import telemetry`` → telemetry.counter_inc(...)
            b, orig = mod.from_imports[base.id]
            cand = f"{b}.{orig}" if b else orig
            if cand in modules:
                tgt = cand
        if tgt and tgt in modules:
            target_mod = modules[tgt]
            if name in target_mod.toplevel:
                return [target_mod.toplevel[name]]
            return []
    if name in PROTO_CHA_STOPLIST or name.startswith("__"):
        return []
    # lock analysis demands precision graftlint's G-rules don't: an
    # ambiguous class-hierarchy match manufactures phantom self-edges
    # (e.g. `h.observe(...)` under MetricsRegistry._lock resolving to
    # MetricsRegistry.observe instead of Histogram.observe), so only
    # uniquely-named methods resolve here
    cands = all_methods.get(name, [])
    if len(cands) == 1:
        return list(cands)
    return []


def _blocking_desc(call: ast.Call) -> Optional[str]:
    ds = dotted(call.func)
    if ds is None:
        return None
    last = ds.split(".")[-1]
    if last in BLOCKING_ALWAYS:
        return f"{ds}(...)"
    if last in BLOCKING_IF_UNTIMED:
        has_timeout = any(kw.arg == "timeout" for kw in call.keywords)
        if not call.args and not has_timeout:
            return f"untimed {ds}()"
    return None


# ---------------------------------------------------------------------------
# per-function scan
# ---------------------------------------------------------------------------


def _scan_function(f: _FnFacts, modules: Dict[str, ModuleInfo],
                   all_methods: Dict[str, List[FuncInfo]]) -> None:
    mod, fi = f.mod, f.fi
    # bare lock.acquire() acquisitions currently open, in document order;
    # the matching release() pops them. Statements are walked in source
    # order, so the window [acquire() .. release()] is lexical — the
    # ``acquire(); try: ... finally: release()`` idiom resolves correctly
    # (finalbody follows the try body in document order).
    acquired: List[LockId] = []

    def walk(node: ast.AST, held: Tuple[LockId, ...]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # separate FuncInfo, scanned on its own
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new_held = held
            for item in node.items:
                walk_children(item.context_expr, held)
                lock = _lock_id(item.context_expr, mod, fi)
                if lock is None:
                    continue
                f.own_locks.add(lock)
                for h in new_held + tuple(acquired):
                    f.direct_edges.append((h, lock, node.lineno))
                new_held = new_held + (lock,)
            for stmt in node.body:
                walk(stmt, new_held)
            return
        if isinstance(node, ast.Call):
            if (isinstance(node.func, ast.Attribute) and not node.args
                    and not node.keywords):
                # plain lock.acquire()/release() only: a conditional
                # acquire(blocking=False)/acquire(timeout=...) may FAIL to
                # take the lock, so treating it as held would fabricate
                # edges — out of scope, like the docstring says
                lock = _lock_id(node.func.value, mod, fi)
                if lock is not None and node.func.attr == "acquire":
                    f.own_locks.add(lock)
                    for h in held + tuple(acquired):
                        f.direct_edges.append((h, lock, node.lineno))
                    acquired.append(lock)
                    walk_children(node, held)
                    return
                if lock is not None and node.func.attr == "release":
                    for i in range(len(acquired) - 1, -1, -1):
                        if acquired[i] == lock:
                            del acquired[i]
                            break
                    walk_children(node, held)
                    return
            held_now = held + tuple(acquired)
            if held_now:
                desc = _blocking_desc(node)
                if desc is not None:
                    f.direct_blocks.append((desc, node.lineno, held_now[-1]))
            for callee in _resolve_callees(node, mod, fi, modules,
                                           all_methods):
                f.calls.append((id(callee.node), node.lineno, held_now))
        walk_children(node, held)

    def walk_children(node: ast.AST, held: Tuple[LockId, ...]) -> None:
        for child in ast.iter_child_nodes(node):
            walk(child, held)

    walk_children(fi.node, ())


def _fixpoint(facts: Dict[int, _FnFacts]) -> None:
    for f in facts.values():
        f.trans_locks = set(f.own_locks)
        f.trans_blocks = []
        # every lexical blocking call counts transitively (under a lock or
        # not) — the CALLER may be holding one
        _collect_own_blocks(f)
    changed = True
    while changed:
        changed = False
        for f in facts.values():
            for callee_key, _line, _held in f.calls:
                callee = facts.get(callee_key)
                if callee is None:
                    continue
                before = len(f.trans_locks)
                f.trans_locks |= callee.trans_locks
                if len(f.trans_locks) != before:
                    changed = True
                for entry in callee.trans_blocks:
                    if entry not in f.trans_blocks:
                        f.trans_blocks.append(entry)
                        changed = True


def _collect_own_blocks(f: _FnFacts) -> None:
    from .model import _own_nodes

    for node in _own_nodes(f.fi.node):
        if isinstance(node, ast.Call):
            desc = _blocking_desc(node)
            entry = (desc, f"{f.mod.rel}:{node.lineno}")
            if desc is not None and entry not in f.trans_blocks:
                f.trans_blocks.append(entry)


# ---------------------------------------------------------------------------
# findings
# ---------------------------------------------------------------------------


def _emit(facts: Dict[int, _FnFacts]) -> List[Finding]:
    findings: List[Finding] = []
    # P009 — direct, then one-hop through calls
    seen: Set[tuple] = set()
    for f in facts.values():
        for desc, line, lock in f.direct_blocks:
            key = (f.mod.rel, line, "P009")
            if key not in seen:
                seen.add(key)
                findings.append(_mk_lock(
                    "P009", f, line,
                    f"blocking call {desc} while holding "
                    f"{_fmt(lock)} — every other thread contending on the "
                    "lock stalls for the full blocking duration"))
        for callee_key, line, held in f.calls:
            if not held:
                continue
            callee = facts.get(callee_key)
            if callee is None or not callee.trans_blocks:
                continue
            desc, where = callee.trans_blocks[0]
            key = (f.mod.rel, line, "P009")
            if key in seen:
                continue
            seen.add(key)
            findings.append(_mk_lock(
                "P009", f, line,
                f"call to {callee.fi.qualname}() while holding "
                f"{_fmt(held[-1])} — it blocks on {desc} ({where})"))

    # P008 — edges, then cyclic SCCs
    edges: Dict[Tuple[LockId, LockId], Tuple[_FnFacts, int]] = {}
    for f in facts.values():
        for a, b, line in f.direct_edges:
            edges.setdefault((a, b), (f, line))
        for callee_key, line, held in f.calls:
            callee = facts.get(callee_key)
            if callee is None:
                continue
            for lock in callee.trans_locks:
                for h in held:
                    edges.setdefault((h, lock), (f, line))
    adj: Dict[LockId, Set[LockId]] = {}
    for (a, b) in edges:
        adj.setdefault(a, set()).add(b)
        adj.setdefault(b, set())
    sccs = _cyclic_sccs(adj)
    for (a, b), (f, line) in sorted(
            edges.items(), key=lambda kv: (kv[1][0].mod.rel, kv[1][1])):
        in_cycle = a == b or any(a in scc and b in scc for scc in sccs)
        if not in_cycle:
            continue
        if a == b:
            msg = (f"{_fmt(a)} re-acquired while already held — "
                   "threading.Lock is non-reentrant; this self-deadlocks")
        else:
            other = edges.get((b, a))
            where = (f" (reverse order at {other[0].mod.rel}:{other[1]})"
                     if other else "")
            msg = (f"{_fmt(b)} acquired while holding {_fmt(a)}, but the "
                   f"opposite order also exists{where} — cyclic lock "
                   "order can deadlock the comm thread against the trainer")
        findings.append(_mk_lock("P008", f, line, msg))
    return findings


def _cyclic_sccs(adj: Dict[LockId, Set[LockId]]) -> List[Set[LockId]]:
    """Tarjan SCCs with more than one node (self-loops handled by caller)."""
    index: Dict[LockId, int] = {}
    low: Dict[LockId, int] = {}
    on_stack: Set[LockId] = set()
    stack: List[LockId] = []
    out: List[Set[LockId]] = []
    counter = [0]

    def strongconnect(v: LockId) -> None:
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        for w in adj.get(v, ()):
            if w not in index:
                strongconnect(w)
                low[v] = min(low[v], low[w])
            elif w in on_stack:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            scc: Set[LockId] = set()
            while True:
                w = stack.pop()
                on_stack.discard(w)
                scc.add(w)
                if w == v:
                    break
            if len(scc) > 1:
                out.append(scc)

    for v in list(adj):
        if v not in index:
            strongconnect(v)
    return out


def _fmt(lock: LockId) -> str:
    return f"`{lock[0]}.{lock[1]}`"


def _mk_lock(rule: str, f: _FnFacts, line: int, message: str) -> Finding:
    return Finding(rule=rule, path=f.mod.rel, line=line, col=0,
                   message=message, line_text=f.mod.line_text(line))
