"""Protocol rules P001–P007 over the extracted :class:`ProtoModel`.

P001 sent-but-never-handled (incl. handled-only-on-the-wrong-role)
P002 handled-but-never-sent
P003 type-constant drift (stale attribute refs, literals shadowing
     constants, duplicate wire values in one define class, dead constants)
P004 replay-unsafe handlers (round-state mutation without a round guard)
P005 no-path-to-finish (FSM classes that can never terminate; terminal
     messages nobody sends)
P006 sends bypassing the delivery layer's stamping
P007 payload-store writes skipping the sha256 digest

P006/P007 exempt ``fedml_tpu/core/distributed/`` — that package IS the
delivery plane the rules protect.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ..graftlint.analyzer import ModuleInfo, dotted
from .findings import Finding
from .model import ClassFacts, ProtoModel, _own_nodes

DELIVERY_PLANE_PREFIX = "fedml_tpu/core/distributed/"

# tokens whose presence in an enclosing function marks the digest path
DIGEST_TOKENS = ("arrays_digest", "PAYLOAD_SHA256")


def _mk(rule: str, mod_rel: str, line: int, message: str,
        modules_by_rel: Dict[str, ModuleInfo]) -> Finding:
    mod = modules_by_rel.get(mod_rel)
    line_text = mod.line_text(line) if mod is not None else ""
    return Finding(rule=rule, path=mod_rel, line=line, col=0,
                   message=message, line_text=line_text)


def check_protocol(model: ProtoModel,
                   modules: Dict[str, ModuleInfo]) -> List[Finding]:
    by_rel = {m.rel: m for m in modules.values()}
    findings: List[Finding] = []
    findings += _check_flow_graph(model, by_rel)
    findings += _check_drift(model, by_rel)
    findings += _check_replay_safety(model, by_rel)
    findings += _check_termination(model, by_rel)
    findings += _check_delivery_invariants(model, modules, by_rel)
    return findings


# ---------------------------------------------------------------------------
# P001 / P002 — the message-flow graph
# ---------------------------------------------------------------------------


def _check_flow_graph(model: ProtoModel, by_rel) -> List[Finding]:
    findings: List[Finding] = []
    for value in sorted(model.values()):
        sends = model.sends.get(value, [])
        regs = model.handlers.get(value, [])
        if sends and not regs:
            for s in sends:
                findings.append(_mk(
                    "P001", s.rel, s.line,
                    f"message type {value!r} is sent here but no "
                    "register_message_receive_handler site handles it "
                    "anywhere — the message is silently dropped by every "
                    "receiver", by_rel))
        elif regs and not sends:
            for r in regs:
                findings.append(_mk(
                    "P002", r.rel, r.line,
                    f"message type {value!r} is handled here but never "
                    "sent by any peer — this handler is dead code (or the "
                    "sender was renamed away)", by_rel))
        elif sends and regs:
            findings += _check_roles(model, value, sends, regs, by_rel)
    return findings


def _check_roles(model: ProtoModel, value: str, sends, regs,
                 by_rel) -> List[Finding]:
    """Direction check for C2S_* / S2C_* named constants: the type must be
    handled on the receiving role (and sent from the originating one)."""
    direction = model.direction(value)
    if direction is None:
        return []
    recv_role = "server" if direction == "c2s" else "client"
    send_role = "client" if direction == "c2s" else "server"
    findings: List[Finding] = []

    def role_of(cls: Optional[str], rel: str) -> Optional[str]:
        cf = model.classes.get((rel, cls)) if cls else None
        return cf.role if cf is not None else None

    reg_roles = {role_of(r.cls, r.rel) for r in regs}
    if reg_roles and None not in reg_roles and recv_role not in reg_roles:
        r = regs[0]
        findings.append(_mk(
            "P001", r.rel, r.line,
            f"{direction.upper()} message type {value!r} is registered "
            f"only on {'/'.join(sorted(x for x in reg_roles if x))} "
            f"managers — the receiving role ({recv_role}) has no handler, "
            "so the message is dropped where it matters", by_rel))
    for s in sends:
        r = role_of(s.cls, s.rel)
        if r is not None and r != send_role:
            findings.append(_mk(
                "P001", s.rel, s.line,
                f"{direction.upper()} message type {value!r} is sent from "
                f"a {r}-role manager ({s.cls}) — the naming convention "
                f"says only the {send_role} originates it", by_rel))
    return findings


# ---------------------------------------------------------------------------
# P003 — type-constant drift
# ---------------------------------------------------------------------------


def _check_drift(model: ProtoModel, by_rel) -> List[Finding]:
    findings: List[Finding] = []
    for rel, _cls, _method, ref in model.missing_refs:
        findings.append(_mk(
            "P003", rel, ref.line,
            f"{ref.owner}.{ref.attr} does not exist on the protocol class "
            f"{ref.owner} — a renamed/removed MSG_TYPE constant; this "
            "raises AttributeError the first time the path runs", by_rel))
    for rel, _cls, _method, ref in model.literal_refs:
        aliases = model.value_to_constants.get(ref.value or "", [])
        if aliases:
            names = ", ".join(sorted(c.qualname for c in aliases))
            findings.append(_mk(
                "P003", rel, ref.line,
                f"raw string {ref.value!r} at a message-type position "
                f"duplicates the protocol constant {names} — a rename in "
                "the define class silently strands this site", by_rel))
    # duplicate wire values inside one define class (per defining module:
    # two packages may legitimately both name their define class MyMessage)
    for (_mod_name, owner), consts in sorted(model.constants_by_key.items()):
        seen: Dict[str, str] = {}
        for attr, c in consts.items():
            first = seen.get(c.value)
            if first is not None:
                findings.append(_mk(
                    "P003", c.rel, c.line,
                    f"{owner}.{attr} re-uses wire value {c.value!r} already "
                    f"bound to {owner}.{first} — two FSM edges collapse "
                    "into one on the wire", by_rel))
            else:
                seen[c.value] = attr
    # dead constants: defined, never at any send/registration site
    for c in model.constants:
        if not model.sends.get(c.value) and not model.handlers.get(c.value):
            findings.append(_mk(
                "P003", c.rel, c.line,
                f"{c.qualname} ({c.value!r}) is defined but never sent nor "
                "handled anywhere — dead protocol surface (or the use "
                "sites drifted to a different constant)", by_rel))
    return findings


# ---------------------------------------------------------------------------
# P004 — replay-unsafe handlers
# ---------------------------------------------------------------------------


def _check_replay_safety(model: ProtoModel, by_rel) -> List[Finding]:
    findings: List[Finding] = []
    seen: Set[tuple] = set()
    for cf in model.classes.values():
        for reg in cf.registrations:
            if reg.handler is None:
                continue
            closure = cf.closure(reg.handler)
            if not closure:
                continue
            guarded = any(mf.has_round_compare for mf in closure)
            if guarded:
                continue
            mutations = []
            for mf in closure:
                mutations += [(line, "self.round_idx") for line in
                              mf.round_writes]
                mutations += [(line, f"self.{attr}[...]")
                              for attr, line in mf.subscript_writes]
            if not mutations:
                continue
            line, what = min(mutations)
            key = (cf.rel, cf.name, reg.handler, line)
            if key in seen:
                continue
            seen.add(key)
            findings.append(_mk(
                "P004", cf.rel, line,
                f"handler {cf.name}.{reg.handler} (for "
                f"{reg.value or '?'!r}) mutates round state ({what}) "
                "without any round comparison in its call closure — a "
                "replayed or stale message re-enters the round "
                "(PR 4 replay-idempotence contract)", by_rel))
    return findings


# ---------------------------------------------------------------------------
# P005 — termination
# ---------------------------------------------------------------------------


def _check_termination(model: ProtoModel, by_rel) -> List[Finding]:
    findings: List[Finding] = []
    for cf in model.classes.values():
        if not cf.registrations:
            continue
        first = min(r.line for r in cf.registrations)
        if not cf.finish_anywhere:
            findings.append(_mk(
                "P005", cf.rel, first,
                f"{cf.name} registers message handlers but no method ever "
                "calls self.finish() or done.set() — the receive loop can "
                "never terminate (protocol deadlock on shutdown)", by_rel))
            continue
        # pairing check: the terminal handlers' trigger types must be sent
        terminal_regs = [
            r for r in cf.registrations
            if r.handler is not None
            and any(mf.finishes for mf in cf.closure(r.handler))
        ]
        for r in terminal_regs:
            if r.value is not None and not model.sends.get(r.value):
                findings.append(_mk(
                    "P005", r.rel, r.line,
                    f"{cf.name}'s only path to finish() runs on "
                    f"{r.value!r}, which no peer ever sends — both roles "
                    "block forever waiting on each other", by_rel))
    return findings


# ---------------------------------------------------------------------------
# P006 / P007 — delivery invariants
# ---------------------------------------------------------------------------


def _check_delivery_invariants(model: ProtoModel,
                               modules: Dict[str, ModuleInfo],
                               by_rel) -> List[Finding]:
    findings: List[Finding] = []
    for mod in modules.values():
        if mod.rel.startswith(DELIVERY_PLANE_PREFIX):
            continue
        for fi in mod.funcs_by_node.values():
            fn_src = None
            for node in _own_nodes(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                ds = dotted(node.func)
                if ds is None:
                    continue
                if ".com_manager.send_message" in f".{ds}":
                    findings.append(_mk(
                        "P006", mod.rel, node.lineno,
                        "raw backend send (com_manager.send_message) "
                        "bypasses FedMLCommManager.send_message — the "
                        "message leaves without its seq/epoch stamp, "
                        "payload offload or retry policy, so the "
                        "receiver's dedup window cannot recognize its "
                        "duplicates", by_rel))
                if ".payload_store.put" in f".{ds}":
                    if fn_src is None:
                        try:
                            fn_src = ast.unparse(fi.node)
                        except Exception:  # pragma: no cover
                            fn_src = ""
                    if not any(tok in fn_src for tok in DIGEST_TOKENS):
                        findings.append(_mk(
                            "P007", mod.rel, node.lineno,
                            "payload-store write without a sha256 digest "
                            "in the enclosing function — attach "
                            "MSG_ARG_KEY_PAYLOAD_SHA256 (arrays_digest) "
                            "before offloading, or a torn/corrupt blob "
                            "reaches the FSM unverified", by_rel))
    return findings
