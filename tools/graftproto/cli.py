"""graftproto CLI: ``python -m tools.graftproto [paths...]``.

Thin suite definition over the shared driver
(:mod:`tools.graftlint.clikit` — flags, baseline handling, rendering, and
the exit-code contract live there, shared with graftlint). Exit codes:
0 clean (after baseline + pragmas), 1 findings, 2 usage error OR analyzer
crash.

The JSON report (``--format json`` / ``--json``) adds ``coverage``: the
per-wire-value flow-graph classification (constants, send/handler site
counts), so future PRs can diff protocol surface alongside finding counts.
"""

from __future__ import annotations

import argparse
from typing import Dict, List, Optional, Tuple

from ..graftlint import clikit
from .analyzer import DEFAULT_BASELINE_RELPATH, analyze_paths_with_model
from .findings import PROTO_RULES, Finding


def _analyze(args: argparse.Namespace,
             repo_root: str) -> Tuple[List[Finding], Dict]:
    findings, model = analyze_paths_with_model(args.paths,
                                               repo_root=repo_root)
    return findings, {"coverage": model.coverage()}


def main(argv: Optional[List[str]] = None) -> int:
    return clikit.run_suite(
        argv,
        tool="graftproto",
        description="static protocol & concurrency verification of the "
                    "distributed comm plane: message-flow graph, FSM "
                    "replay/termination, delivery invariants, lock order",
        rules=PROTO_RULES,
        analyze=_analyze,
        baseline_relpath=DEFAULT_BASELINE_RELPATH,
    )


if __name__ == "__main__":
    raise SystemExit(main())
