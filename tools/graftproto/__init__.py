"""graftproto — static protocol & concurrency verification of the
distributed comm plane (sibling suite to :mod:`tools.graftlint`).

Rules (docs/graftproto.md has the catalog with worked examples):

- **P001 sent-but-never-handled** / **P002 handled-but-never-sent** — the
  message-flow graph: every ``Message(MSG_TYPE_*, ...)`` construction is
  resolved (including parameter-typed helpers) and cross-checked against
  every ``register_message_receive_handler`` site, value-keyed; C2S_*/S2C_*
  naming is checked against the registering/sending role.
- **P003 type-constant-drift** — stale ``MSG_TYPE_*`` attribute refs, raw
  string literals shadowing define-class constants, duplicate wire values
  in one define class, dead constants.
- **P004 replay-unsafe-handler** — handlers that mutate round state
  (``self.round_idx`` writes, keyed stores) with no round comparison in
  their call closure (the PR 4 replay-idempotence contract).
- **P005 no-path-to-finish** — FSM classes that can never terminate, and
  terminal messages no peer sends (protocol deadlock).
- **P006 send-bypasses-delivery** / **P007 payload-write-skips-digest** —
  the delivery invariants: seq/epoch stamping and sha256 digesting are
  only enforced on the ``FedMLCommManager.send_message`` path.
- **P008 lock-order-inversion** / **P009 blocking-call-under-lock** —
  lock-acquisition graph cycles and blocking calls (untimed join/get/wait,
  recv, fsync, sleep) while holding a lock.

Suppression: ``# graftproto: disable=P00X`` pragmas (same machinery as
graftlint, own marker) and ``tools/graftproto/baseline.json``.
"""

from .analyzer import analyze_paths, analyze_paths_with_model  # noqa: F401
from .findings import PROTO_RULES, Finding  # noqa: F401
from .model import build_model, enumerate_msg_constants  # noqa: F401
