"""graftproto rule registry (P001–P009), merged into the shared graftlint
Finding infrastructure so both suites render/baseline/JSON identically."""

from __future__ import annotations

from typing import Dict, Tuple

from ..graftlint.findings import Finding, register_rules

# rule id -> (title, autofix hint)
PROTO_RULES: Dict[str, Tuple[str, str]] = {
    "P001": (
        "sent-but-never-handled",
        "register a handler for the type on the receiving role's manager "
        "(register_message_receive_handler), or delete the dead send; a "
        "C2S_* type needs a *Server* manager handler, an S2C_* type a "
        "*Client* one",
    ),
    "P002": (
        "handled-but-never-sent",
        "add the send on the peer role, or delete the dead registration — "
        "a handler waiting on a message nobody sends blocks that FSM "
        "forever",
    ),
    "P003": (
        "type-constant-drift",
        "reference the MSG_TYPE_* constant from the protocol's "
        "message-define class instead of a raw string / stale attribute; "
        "keep every wire value defined exactly once per protocol class",
    ),
    "P004": (
        "replay-unsafe-handler",
        "guard round-state mutation behind a round comparison (the "
        "_replay_guard/_is_stale pattern): read the message's ROUND_IDX "
        "and compare it against the FSM's current round before mutating",
    ),
    "P005": (
        "no-path-to-finish",
        "give the FSM a terminal edge: some handler (or a method it "
        "reaches) must call self.finish()/self.done.set(), and the "
        "message type that triggers it must actually be sent by the peer",
    ),
    "P006": (
        "send-bypasses-delivery",
        "send through FedMLCommManager.send_message so the message gets "
        "its seq/epoch stamp, payload offload and retry policy — never "
        "call the raw backend (com_manager.send_message) from FSM code",
    ),
    "P007": (
        "payload-write-skips-digest",
        "compute arrays_digest(...) and attach MSG_ARG_KEY_PAYLOAD_SHA256 "
        "before handing arrays to the payload store — undigested blobs "
        "defeat the receiver's corruption check",
    ),
    "P008": (
        "lock-order-inversion",
        "acquire the locks in one global order everywhere (or collapse "
        "them into one lock); a cyclic acquisition order deadlocks the "
        "comm thread against the trainer under load",
    ),
    "P009": (
        "blocking-call-under-lock",
        "move the blocking call (join/recv/untimed get/wait/fsync/sleep) "
        "outside the ``with lock:`` block — snapshot state under the "
        "lock, then block lock-free",
    ),
}

register_rules(PROTO_RULES)

__all__ = ["Finding", "PROTO_RULES"]
