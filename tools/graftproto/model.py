"""Protocol model: AST extraction of the distributed comm plane.

Everything is syntactic (no import of analyzed code), built on graftlint's
module index. The model captures, per scanned tree:

- **message-type constants** — ``MSG_TYPE_* = "wire_value"`` class attributes
  (the ``message_define.py`` convention, plus CommunicationConstants and the
  flow DSL's class constants);
- **send sites** — every ``Message(<type>, ...)`` construction, with the
  type expression resolved to a constant, a string literal, or a function
  parameter (parameter-typed helpers like ``_broadcast_model(msg_type)`` are
  resolved through their intra-class call sites);
- **handler registrations** — every ``register_message_receive_handler(
  <type>, <handler>)`` site (including local aliases of the bound method);
- **per-class facts** — method send sets, intra-class call edges, round-
  state mutations, round comparisons, and ``finish()``/``done.set()`` calls.

The flow graph is keyed by **wire value**, not constant name, so aliases
(``MyMessage.MSG_TYPE_CONNECTION_IS_READY`` vs ``CommunicationConstants.
MSG_TYPE_CONNECTION_IS_READY``) merge into one node exactly as they do on
the wire.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..graftlint.analyzer import FuncInfo, ModuleInfo, dotted

MSG_TYPE_PREFIX = "MSG_TYPE"

# dotted-call suffixes that mark an FSM terminal edge
FINISH_CALLS = ("finish",)
FINISH_EVENT_CALLS = ("done.set",)

# the flow DSL's dispatch wire value (FedMLAlgorithmFlow.MSG_TYPE_FLOW):
# ``add_flow(name, callback, role)`` registers ``callback`` as a handler
# the flow plane invokes from its MSG_TYPE_FLOW handler — without modeling
# that, callbacks routed through the DSL are invisible to P001/P002 (a
# flow-only manager looks like it sends 'flow_step' into the void) and
# their round-state mutations escape P004/P005 entirely.
FLOW_REG_METHOD = "add_flow"
FLOW_WIRE_FALLBACK = "flow_step"


class MsgConstant:
    __slots__ = ("owner", "attr", "value", "rel", "line")

    def __init__(self, owner: str, attr: str, value: str, rel: str,
                 line: int):
        self.owner = owner      # defining class name
        self.attr = attr        # MSG_TYPE_* attribute name
        self.value = value      # wire string
        self.rel = rel          # repo-relative module path
        self.line = line

    @property
    def qualname(self) -> str:
        return f"{self.owner}.{self.attr}"


class TypeRef:
    """A resolved message-type expression at a send/registration site."""

    __slots__ = ("kind", "value", "owner", "attr", "param", "line")

    def __init__(self, kind: str, line: int, value: Optional[str] = None,
                 owner: Optional[str] = None, attr: Optional[str] = None,
                 param: Optional[str] = None):
        self.kind = kind  # const | literal | param | missing | unknown
        self.value = value
        self.owner = owner
        self.attr = attr
        self.param = param
        self.line = line


class SendSite:
    __slots__ = ("rel", "cls", "method", "line", "value", "ref")

    def __init__(self, rel: str, cls: Optional[str], method: str, line: int,
                 value: str, ref: TypeRef):
        self.rel = rel
        self.cls = cls
        self.method = method
        self.line = line
        self.value = value
        self.ref = ref


class HandlerReg:
    __slots__ = ("rel", "cls", "method", "line", "value", "ref", "handler")

    def __init__(self, rel: str, cls: Optional[str], method: str, line: int,
                 value: Optional[str], ref: TypeRef,
                 handler: Optional[str]):
        self.rel = rel
        self.cls = cls            # registering class
        self.method = method      # method containing the registration
        self.line = line
        self.value = value        # wire value (None if unresolved)
        self.ref = ref
        self.handler = handler    # handler method name, or None for lambdas


class MethodFacts:
    __slots__ = ("name", "fi", "sends", "self_calls", "finishes",
                 "round_writes", "subscript_writes", "has_round_compare")

    def __init__(self, name: str, fi: FuncInfo):
        self.name = name
        self.fi = fi
        self.sends: List[TypeRef] = []
        # (callee name, positional arg exprs, keyword arg exprs, line)
        self.self_calls: List[Tuple[str, List[ast.expr],
                                    Dict[str, ast.expr], int]] = []
        self.finishes = False
        self.round_writes: List[int] = []       # self.round_idx = ... lines
        self.subscript_writes: List[Tuple[str, int]] = []  # self.X[...] = ...
        self.has_round_compare = False


class ClassFacts:
    __slots__ = ("name", "rel", "module", "methods", "registrations",
                 "finish_anywhere")

    def __init__(self, name: str, rel: str, module: ModuleInfo):
        self.name = name
        self.rel = rel
        self.module = module
        self.methods: Dict[str, MethodFacts] = {}
        self.registrations: List[HandlerReg] = []
        self.finish_anywhere = False

    @property
    def role(self) -> Optional[str]:
        """Comm-plane role by naming convention (None = undetermined)."""
        if "Server" in self.name:
            return "server"
        if "Client" in self.name:
            return "client"
        return None

    def closure(self, method: str) -> List[MethodFacts]:
        """``method`` plus every same-class method reachable via self-calls."""
        seen: Set[str] = set()
        order: List[MethodFacts] = []
        work = [method]
        while work:
            name = work.pop()
            if name in seen:
                continue
            seen.add(name)
            mf = self.methods.get(name)
            if mf is None:
                continue
            order.append(mf)
            for callee, _a, _k, _l in mf.self_calls:
                if callee not in seen:
                    work.append(callee)
        return order


class ProtoModel:
    def __init__(self) -> None:
        self.constants: List[MsgConstant] = []
        # keyed by (defining module name, class name): the reference-FedML
        # convention names every define class `MyMessage`, so a bare-name
        # key would silently merge unrelated protocols the moment a second
        # package grows its own define class
        self.constants_by_key: Dict[Tuple[str, str],
                                    Dict[str, MsgConstant]] = {}
        self.owner_index: Dict[str, List[Tuple[str, str]]] = {}
        self.value_to_constants: Dict[str, List[MsgConstant]] = {}
        self.classes: Dict[Tuple[str, str], ClassFacts] = {}  # (rel, name)
        self.sends: Dict[str, List[SendSite]] = {}      # value -> sites
        self.handlers: Dict[str, List[HandlerReg]] = {}  # value -> regs
        self.missing_refs: List[Tuple[str, Optional[str], str, TypeRef]] = []
        self.literal_refs: List[Tuple[str, Optional[str], str, TypeRef]] = []

    # -- queries used by the rules and the coverage gate ---------------------
    def values(self) -> Set[str]:
        return set(self.sends) | set(self.handlers) | set(
            self.value_to_constants)

    def classify_value(self, value: str) -> str:
        sent = bool(self.sends.get(value))
        handled = bool(self.handlers.get(value))
        if sent and handled:
            return "sent+handled"
        if sent:
            return "sent-only"
        if handled:
            return "handled-only"
        return "unused"

    def direction(self, value: str) -> Optional[str]:
        """'c2s' / 's2c' when every alias constant name agrees, else None."""
        dirs = set()
        for c in self.value_to_constants.get(value, []):
            if "C2S" in c.attr:
                dirs.add("c2s")
            if "S2C" in c.attr:
                dirs.add("s2c")
        return dirs.pop() if len(dirs) == 1 else None

    def coverage(self) -> Dict[str, Dict[str, object]]:
        """Machine-readable per-value classification (for --json diffing)."""
        out: Dict[str, Dict[str, object]] = {}
        for value in sorted(self.values()):
            out[value] = {
                "classification": self.classify_value(value),
                "constants": sorted(
                    c.qualname for c in self.value_to_constants.get(value, [])
                ),
                "send_sites": len(self.sends.get(value, [])),
                "handler_sites": len(self.handlers.get(value, [])),
            }
        return out


# ---------------------------------------------------------------------------
# extraction
# ---------------------------------------------------------------------------


def owning_class(fi: FuncInfo) -> Optional[str]:
    f: Optional[FuncInfo] = fi
    while f is not None:
        if f.class_name:
            return f.class_name
        f = f.parent
    return None


def owning_method(fi: FuncInfo) -> str:
    """Nearest enclosing class method (or top-level function) name."""
    f, last = fi, fi
    while f is not None:
        last = f
        if f.class_name:
            return f.name
        f = f.parent
    return last.name


def _method_params(fi: FuncInfo) -> List[str]:
    params = fi.params()
    if params and params[0] in ("self", "cls"):
        params = params[1:]
    return params


def build_model(modules: Dict[str, ModuleInfo]) -> ProtoModel:
    model = ProtoModel()
    _collect_constants(modules, model)
    for mod in modules.values():
        _collect_module_facts(mod, model)
    _resolve_param_sends(model)
    return model


def _collect_constants(modules: Dict[str, ModuleInfo],
                       model: ProtoModel) -> None:
    for mod in modules.values():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for stmt in node.body:
                if not (isinstance(stmt, ast.Assign)
                        and len(stmt.targets) == 1
                        and isinstance(stmt.targets[0], ast.Name)):
                    continue
                name = stmt.targets[0].id
                if not name.startswith(MSG_TYPE_PREFIX):
                    continue
                if not (isinstance(stmt.value, ast.Constant)
                        and isinstance(stmt.value.value, str)):
                    continue
                c = MsgConstant(node.name, name, stmt.value.value, mod.rel,
                                stmt.lineno)
                model.constants.append(c)
                key = (mod.name, node.name)
                if key not in model.constants_by_key:
                    model.constants_by_key[key] = {}
                    model.owner_index.setdefault(node.name, []).append(key)
                model.constants_by_key[key][name] = c
                model.value_to_constants.setdefault(c.value, []).append(c)


def _owner_candidates(owner: str, mod: ModuleInfo,
                      model: ProtoModel) -> List[Tuple[str, str]]:
    """Define-class keys a bare class name may resolve to FROM ``mod``:
    the module's own class first, then the from-import target, then (only
    when unambiguous or nothing local matched) every same-named class."""
    keys = model.owner_index.get(owner, [])
    if len(keys) <= 1:
        return keys
    local = [k for k in keys if k[0] == mod.name]
    if local:
        return local
    imp = mod.from_imports.get(owner)
    if imp:
        imported = [k for k in keys if k[0] == imp[0]]
        if imported:
            return imported
    return keys


def _resolve_type_expr(expr: ast.expr, mod: ModuleInfo, cls: Optional[str],
                       fi: FuncInfo, model: ProtoModel,
                       _depth: int = 0) -> TypeRef:
    line = getattr(expr, "lineno", fi.node.lineno)
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return TypeRef("literal", line, value=expr.value)
    if isinstance(expr, ast.Name):
        if expr.id in _method_params(fi):
            return TypeRef("param", line, param=expr.id)
        if _depth < 2:
            # single-assignment local: t = MyMessage.MSG_TYPE_X; Message(t)
            for node in ast.walk(fi.node):
                if (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and node.targets[0].id == expr.id):
                    return _resolve_type_expr(node.value, mod, cls, fi,
                                              model, _depth + 1)
        return TypeRef("unknown", line)
    ds = dotted(expr)
    if ds is None:
        return TypeRef("unknown", line)
    parts = ds.split(".")
    if len(parts) < 2:
        return TypeRef("unknown", line)
    attr = parts[-1]
    owner = parts[-2]
    if owner in ("self", "cls"):
        owner = cls or owner
    candidates = _owner_candidates(owner, mod, model)
    if candidates:
        for key in candidates:
            c = model.constants_by_key[key].get(attr)
            if c is not None:
                return TypeRef("const", line, value=c.value, owner=owner,
                               attr=attr)
        if attr.startswith(MSG_TYPE_PREFIX):
            # absent from EVERY candidate define class -> renamed/removed
            return TypeRef("missing", line, owner=owner, attr=attr)
    return TypeRef("unknown", line)


def _collect_module_facts(mod: ModuleInfo, model: ProtoModel) -> None:
    for fi in mod.funcs_by_node.values():
        if isinstance(fi.node, ast.Lambda):
            continue
        cls = owning_class(fi)
        method = owning_method(fi)
        cf = None
        if cls is not None:
            cf = model.classes.get((mod.rel, cls))
            if cf is None:
                cf = model.classes[(mod.rel, cls)] = ClassFacts(
                    cls, mod.rel, mod)
            mf = cf.methods.get(method)
            if mf is None:
                mf = cf.methods[method] = MethodFacts(method, fi)
        else:
            mf = MethodFacts(method, fi)

        # local aliases of the registration method:
        #   reg = self.register_message_receive_handler
        reg_aliases: Set[str] = set()
        for node in _own_nodes(fi.node):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                vds = dotted(node.value)
                if vds and vds.endswith("register_message_receive_handler"):
                    reg_aliases.add(node.targets[0].id)

        mf.has_round_compare = (mf.has_round_compare
                                or _has_round_guard(fi.node))
        for node in _own_nodes(fi.node):
            if isinstance(node, ast.Call):
                _collect_call(node, mod, cls, method, fi, mf, cf, model,
                              reg_aliases)
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    base = t
                    if isinstance(base, ast.Subscript):
                        inner = base.value
                        if (isinstance(inner, ast.Attribute)
                                and isinstance(inner.value, ast.Name)
                                and inner.value.id == "self"):
                            mf.subscript_writes.append(
                                (inner.attr, t.lineno))
                        continue
                    if (isinstance(base, ast.Attribute)
                            and isinstance(base.value, ast.Name)
                            and base.value.id == "self"
                            and base.attr == "round_idx"):
                        mf.round_writes.append(t.lineno)


# tokens that mark an expression as carrying round/version identity — the
# staleness-era protocol tags models with versions, not just round indices
_ROUND_TOKENS = ("round", "rnd", "version", "staleness")


def _has_round_guard(fn_node: ast.AST) -> bool:
    """True when the function compares round/version identity somewhere.

    Two recognizers:

    1. *textual* — any ``ast.Compare`` whose source mentions a round token
       (``if round_idx < self.round_idx``), the original P004 heuristic;
    2. *dataflow* — a compare over a local name assigned (possibly through
       other locals) from a round-ish expression, e.g.
       ``r = int(msg.get(MyMessage.MSG_ARG_KEY_ROUND_IDX)); if r < cur:``
       — guard variants the textual match is blind to, which previously
       forced pragmas on perfectly replay-safe handlers.
    """
    compares: List[ast.Compare] = []
    assigns: List[Tuple[List[str], ast.expr, str]] = []
    for node in _own_nodes(fn_node):
        if isinstance(node, ast.Compare):
            compares.append(node)
        elif isinstance(node, ast.Assign):
            names = [t.id for t in node.targets if isinstance(t, ast.Name)]
            if names:
                try:
                    rhs = ast.unparse(node.value).lower()
                except Exception:  # pragma: no cover — unparse is total
                    rhs = ""
                assigns.append((names, node.value, rhs))
    tainted: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for names, value, rhs in assigns:
            if all(n in tainted for n in names):
                continue
            src_names = {x.id for x in ast.walk(value)
                         if isinstance(x, ast.Name)}
            if any(tok in rhs for tok in _ROUND_TOKENS) or (
                    src_names & tainted):
                tainted.update(names)
                changed = True
    for cmp_node in compares:
        try:
            text = ast.unparse(cmp_node).lower()
        except Exception:  # pragma: no cover — unparse is total
            text = ""
        if any(tok in text for tok in _ROUND_TOKENS):
            return True
        if any(isinstance(x, ast.Name) and x.id in tainted
               for x in ast.walk(cmp_node)):
            return True
    return False


_FLOW_MODULE_NAMES = ("flow", "fedml_flow")


def _touches_flow_plane(mod: ModuleInfo, model: ProtoModel) -> bool:
    """True when ``mod`` plausibly uses the algorithm-flow DSL: it imports
    the flow module (any form) / FedMLAlgorithmFlow, or defines a
    MSG_TYPE_FLOW constant itself (standalone fixtures)."""
    for base, orig in mod.from_imports.values():
        if (orig in ("FedMLAlgorithmFlow", "FedMLExecutor")
                or orig in _FLOW_MODULE_NAMES          # from pkg import flow
                or base.rsplit(".", 1)[-1] in _FLOW_MODULE_NAMES):
            return True
    for target in mod.imports.values():                # import pkg.flow
        if target.rsplit(".", 1)[-1] in _FLOW_MODULE_NAMES:
            return True
    return any(c.attr == "MSG_TYPE_FLOW" and c.rel == mod.rel
               for c in model.constants)


def _flow_wire_value(model: ProtoModel) -> str:
    """The wire value add_flow callbacks ride on: the scanned tree's
    MSG_TYPE_FLOW constant when present (the shipped flow.py), else the
    canonical literal (standalone fixtures)."""
    for c in model.constants:
        if c.attr == "MSG_TYPE_FLOW":
            return c.value
    return FLOW_WIRE_FALLBACK


def _collect_call(node: ast.Call, mod: ModuleInfo, cls: Optional[str],
                  method: str, fi: FuncInfo, mf: MethodFacts,
                  cf: Optional[ClassFacts], model: ProtoModel,
                  reg_aliases: Set[str]) -> None:
    ds = dotted(node.func)
    last = ds.split(".")[-1] if ds else ""

    # Message(<type>, ...) construction == a send site (everything the
    # managers construct is destined for the wire; zero-arg Message() is
    # the deserialization shell and is skipped)
    if last == "Message" and node.args:
        ref = _resolve_type_expr(node.args[0], mod, cls, fi, model)
        mf.sends.append(ref)
        _index_type_site(model, mod, cls, method, ref, is_send=True)

    # flow-DSL callback registration: add_flow(name, callback, role, ...)
    # == a handler registration for the flow dispatch wire value, with the
    # callback entering the registering class's P004/P005 closure. Gated
    # on the module actually touching the flow plane (imports it, or
    # defines a MSG_TYPE_FLOW constant) — "add_flow" alone is too
    # collision-prone a name to claim for the DSL.
    flow_cb = None
    if ds is not None and ds.split(".")[-1] == FLOW_REG_METHOD:
        if len(node.args) >= 2:
            flow_cb = node.args[1]
        else:  # keyword form: add_flow("train", executor_task=self._fn)
            flow_cb = next((kw.value for kw in node.keywords
                            if kw.arg == "executor_task"), None)
    if flow_cb is not None and _touches_flow_plane(mod, model):
        wire = _flow_wire_value(model)
        handler = None
        cb_ds = dotted(flow_cb)
        if cb_ds is not None:
            handler = (cb_ds.split(".", 1)[1] if cb_ds.startswith("self.")
                       else cb_ds.split(".")[-1])
        reg = HandlerReg(mod.rel, cls, method, node.lineno, wire,
                         TypeRef("flow", node.lineno, value=wire), handler)
        if cf is not None:
            cf.registrations.append(reg)
        model.handlers.setdefault(wire, []).append(reg)

    # handler registration (direct or via a local alias)
    is_reg = (ds is not None
              and ds.endswith("register_message_receive_handler")) or (
        isinstance(node.func, ast.Name) and node.func.id in reg_aliases)
    if is_reg and node.args:
        ref = _resolve_type_expr(node.args[0], mod, cls, fi, model)
        handler = None
        if len(node.args) > 1:
            hds = dotted(node.args[1])
            if hds and hds.startswith("self."):
                handler = hds.split(".", 1)[1]
        reg = HandlerReg(mod.rel, cls, method, node.lineno, ref.value, ref,
                         handler)
        if cf is not None:
            cf.registrations.append(reg)
        if ref.value is not None:
            model.handlers.setdefault(ref.value, []).append(reg)
        _index_type_site(model, mod, cls, method, ref, is_send=False)

    # terminal edges
    if ds is not None and (
            ds in tuple(f"self.{n}" for n in FINISH_CALLS)
            or any(ds.endswith(f".{n}") for n in FINISH_EVENT_CALLS)):
        mf.finishes = True
        if cf is not None:
            cf.finish_anywhere = True

    # intra-class call edge
    if (ds is not None and ds.startswith("self.")
            and len(ds.split(".")) == 2 and cf is not None):
        callee = ds.split(".")[1]
        kwargs = {kw.arg: kw.value for kw in node.keywords if kw.arg}
        mf.self_calls.append((callee, list(node.args), kwargs, node.lineno))


def _index_type_site(model: ProtoModel, mod: ModuleInfo, cls: Optional[str],
                     method: str, ref: TypeRef, is_send: bool) -> None:
    if ref.kind == "missing":
        model.missing_refs.append((mod.rel, cls, method, ref))
        return
    if ref.kind == "literal":
        model.literal_refs.append((mod.rel, cls, method, ref))
    if ref.value is None:
        return
    if is_send:
        model.sends.setdefault(ref.value, []).append(
            SendSite(mod.rel, cls, method, ref.line, ref.value, ref))


def _resolve_param_sends(model: ProtoModel) -> None:
    """Resolve parameter-typed sends (``def _broadcast_model(self,
    msg_type): ... Message(msg_type, ...)``) through intra-class call
    sites, attributing the send to the construction site."""
    for cf in model.classes.values():
        for mf in cf.methods.values():
            param_sends = [r for r in mf.sends if r.kind == "param"]
            if not param_sends:
                continue
            params = _method_params(mf.fi)
            for ref in param_sends:
                if ref.param not in params:
                    continue
                idx = params.index(ref.param)
                for caller in cf.methods.values():
                    for callee, args, kwargs, _line in caller.self_calls:
                        if callee != mf.name:
                            continue
                        arg = kwargs.get(ref.param)
                        if arg is None and idx < len(args):
                            arg = args[idx]
                        if arg is None:
                            continue
                        sub = _resolve_type_expr(
                            arg, cf.module, cf.name, caller.fi, model)
                        if sub.value is not None:
                            model.sends.setdefault(sub.value, []).append(
                                SendSite(cf.rel, cf.name, mf.name, ref.line,
                                         sub.value, sub))
                        elif sub.kind == "missing":
                            model.missing_refs.append(
                                (cf.rel, cf.name, caller.name, sub))


def _own_nodes(root: ast.AST):
    """Nodes lexically in ``root``, not descending into nested defs."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def enumerate_msg_constants(paths: Sequence[str], repo_root: str
                            ) -> List[MsgConstant]:
    """Standalone AST enumeration of every MSG_TYPE_* constant under
    ``paths`` — used by the coverage gate to prove the flow graph has no
    silent gaps (it must classify every constant this finds)."""
    from ..graftlint.analyzer import collect_files, load_modules

    modules = load_modules(collect_files(paths), repo_root)
    model = ProtoModel()
    _collect_constants(modules, model)
    return model.constants
