"""``--equiv``: fused/unfused round structural equivalence under make_jaxpr.

The fused round path (``round_engine.build_round_core``) is a hand-written
mirror of ``FedAvgAPI._train_round`` — defended, until now, only by runtime
parity tests that compare numbers to a tolerance. This module proves the
stronger structural claim: both paths trace to the SAME canonical jaxpr.

How the two traces are aligned:

- **Unfused**: the real ``_train_round`` is traced with its host seams
  pinned — ``_client_sampling`` returns the fixed cohort, ``_gather_cohort``
  returns the wrapper's traced ``(cx, cy, cn)`` arguments, and the
  host-float ``sp_api._masked_mean`` is swapped for the device twin
  (``round_engine._masked_mean``) for the duration of the trace (the host
  pull is the loss-sync seam, outside the compared chain). The new round
  state is read back off the api object.
- **Fused**: ``build_round_core``'s program over the same traced arguments.
  Both wrappers compute ``fold_in``/``split`` on the CONCRETE root key, so
  PRNG material enters both jaxprs as (equal) constants, not equations.
- Returned values are pinned to ``(new_state, train_loss)`` on both sides;
  everything else is dead code and removed by DCE.

Canonicalization (the rules, also documented in docs/graftrep.md):

1. **DCE** — backward liveness from the outputs; unused equations (e.g. the
   fused path's ``examples`` counter) drop out.
2. **Constant folding by content** — consts and literals are labeled by
   ``dtype/shape/sha1(bytes)``, so equal values unify regardless of which
   trace produced them, and alpha-renaming cannot hide a changed constant.
3. **Parallel-safe ordering** — equations are re-scheduled by Kahn's
   algorithm, breaking ties by (primitive, params, operand labels): any two
   topological orders of the same dataflow graph canonicalize identically.
4. **Alpha-renaming** — inputs become ``in0..inN``, scheduled outputs
   ``v0..vN``; sub-jaxprs (pjit/scan bodies) are canonicalized recursively
   and expanded inline so a divergence INSIDE the shared cohort program is
   still named precisely.

Limits: this is structural equality of the traced programs, not of XLA's
optimized HLO; host-side seams (sampling, gather, loss sync, telemetry)
are pinned equal by construction and verified separately by the parity
tests; FedSGD/FedNova share the same aggregate core but have no fused/
unfused *pair* of mirrors to compare.
"""

from __future__ import annotations

import hashlib
import os
import sys
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .findings import Finding

REL_ENGINE = os.path.join("fedml_tpu", "simulation",
                          "round_engine.py").replace(os.sep, "/")


# ---------------------------------------------------------------------------
# jaxpr canonicalization
# ---------------------------------------------------------------------------


def _const_label(val: Any) -> str:
    import numpy as np

    try:
        arr = np.asarray(val)
        h = hashlib.sha1(
            arr.tobytes() + str(arr.dtype).encode() + str(arr.shape).encode()
        ).hexdigest()[:10]
        return f"const[{arr.dtype}{list(arr.shape)}:{h}]"
    except Exception:  # non-array const (rare)
        return f"const[{type(val).__name__}:{val!r}]"


def _aval_str(v: Any) -> str:
    aval = getattr(v, "aval", None)
    if aval is None:
        return "?"
    short = getattr(aval, "str_short", None)
    return short() if callable(short) else str(aval)


def _is_jaxpr_like(v: Any) -> bool:
    return hasattr(v, "eqns") or hasattr(v, "jaxpr")


def _param_label(v: Any, memo: Optional[Dict[int, Tuple]] = None
                 ) -> Tuple[str, Optional[List[str]]]:
    """(stable label for scheduling/diff, expanded sub-lines or None).

    ``memo`` (id(param) → result, scoped to one ``canonicalize`` call so
    ids stay live) keeps sub-jaxpr canonicalization linear: scheduling
    consults every ready eqn's signature repeatedly, and without the memo
    each consult would re-canonicalize the whole pjit/scan body."""
    if memo is not None:
        hit = memo.get(id(v))
        if hit is not None:
            return hit
    if _is_jaxpr_like(v):
        sub = canonicalize(v)
        digest = hashlib.sha1("\n".join(sub).encode()).hexdigest()[:10]
        out: Tuple[str, Optional[List[str]]] = (f"jaxpr:{digest}", sub)
    elif isinstance(v, (list, tuple)) and any(_is_jaxpr_like(x) for x in v):
        labels, subs = [], []
        for x in v:
            lab, sub = _param_label(x, memo)
            labels.append(lab)
            if sub:
                subs.extend(sub)
        out = ("[" + ", ".join(labels) + "]", subs or None)
    elif callable(v):
        out = (f"fn:{getattr(v, '__name__', type(v).__name__)}", None)
    else:
        out = (repr(v), None)
    if memo is not None:
        memo[id(v)] = out
    return out


def canonicalize(closed: Any,
                 _depth: int = 0) -> List[str]:
    """ClosedJaxpr/Jaxpr → canonical line list (see module docstring)."""
    jaxpr = getattr(closed, "jaxpr", closed)
    consts = list(getattr(closed, "consts", ()))
    if len(consts) < len(jaxpr.constvars):
        # raw Jaxpr param (scan body etc.): no const values — label by aval
        consts = None

    names: Dict[Any, str] = {}
    for i, v in enumerate(jaxpr.invars):
        names[v] = f"in{i}:{_aval_str(v)}"
    for i, v in enumerate(jaxpr.constvars):
        if consts is not None:
            names[v] = _const_label(consts[i])
        else:
            names[v] = f"cvar:{_aval_str(v)}"

    def label_of(v: Any) -> str:
        if hasattr(v, "val"):  # Literal
            return _const_label(v.val)
        return names.get(v, "?unbound")

    # DCE: backward liveness from the outputs
    live = {v for v in jaxpr.outvars if not hasattr(v, "val")}
    kept: List[Any] = []
    for eqn in reversed(jaxpr.eqns):
        if any(o in live for o in eqn.outvars):
            kept.append(eqn)
            for iv in eqn.invars:
                if not hasattr(iv, "val"):
                    live.add(iv)
    kept.reverse()

    # Kahn scheduling with deterministic content tie-break. Signatures are
    # memoized per eqn (operand labels are final once an eqn is ready, and
    # eqn_sig only ever runs on ready eqns) and sub-jaxpr canonicalization
    # per param object — without these the scheduler re-canonicalizes the
    # pjit cohort program O(n^2) times.
    defined = set(names)
    remaining = list(kept)
    lines: List[str] = []
    counter = [0]
    param_memo: Dict[int, Tuple] = {}
    sig_memo: Dict[int, Tuple] = {}

    def eqn_sig(eqn: Any) -> Tuple:
        sig = sig_memo.get(id(eqn))
        if sig is not None:
            return sig
        ops = tuple(label_of(v) for v in eqn.invars)
        param_bits = []
        for k in sorted(eqn.params):
            lab, _sub = _param_label(eqn.params[k], param_memo)
            param_bits.append(f"{k}={lab}")
        sig = (eqn.primitive.name, tuple(param_bits), ops)
        sig_memo[id(eqn)] = sig
        return sig

    while remaining:
        ready = [e for e in remaining
                 if all((hasattr(v, "val") or v in defined)
                        for v in e.invars)]
        if not ready:  # cycle cannot happen in a jaxpr; defensive
            ready = remaining[:1]
        chosen = min(ready, key=eqn_sig)
        remaining.remove(chosen)
        prim, params, ops = eqn_sig(chosen)
        outs = []
        for o in chosen.outvars:
            if type(o).__name__ == "DropVar":
                outs.append("_")
                continue
            nm = f"v{counter[0]}:{_aval_str(o)}"
            counter[0] += 1
            names[o] = nm
            defined.add(o)
            outs.append(nm)
        lines.append(f"{', '.join(outs)} = {prim}"
                     f"[{' '.join(params)}] {' '.join(ops)}")
        for k in sorted(chosen.params):
            _lab, sub = _param_label(chosen.params[k], param_memo)
            if sub:
                pad = "  " * (_depth + 1)
                lines.extend(f"{pad}{k}> {ln}" for ln in sub)

    lines.append("return " + " ".join(label_of(v) for v in jaxpr.outvars))
    return lines


def diff_canonical(a: List[str], b: List[str]
                   ) -> Optional[Tuple[int, str, str]]:
    """First diverging (index, line_a, line_b), or None when equal."""
    for i, (la, lb) in enumerate(zip(a, b)):
        if la != lb:
            return i, la, lb
    if len(a) != len(b):
        i = min(len(a), len(b))
        return (i,
                a[i] if i < len(a) else "<end of unfused program>",
                b[i] if i < len(b) else "<end of fused program>")
    return None


# ---------------------------------------------------------------------------
# tracing the two round paths
# ---------------------------------------------------------------------------


def _example_round(api):
    """(per, cohort, cx, cy, cn, state0) — the same example geometry the
    graftlint runtime pass uses."""
    import numpy as np

    per = min(int(api.args.client_num_per_round), api.ds.client_num)
    cohort = np.arange(per)
    cx, cy, cn = api._gather_cohort(cohort)
    return per, cohort, cx, cy, cn, api._round_state()


def trace_fused(api, per: int, cohort, cx, cy, cn, state0,
                round_idx: int = 0,
                core_factory: Optional[Callable] = None):
    """Canonical jaxpr of the fused mirror over traced (state, cx, cy, cn).

    ``core_factory`` defaults to the real ``build_round_core``; tests pass
    a skewed factory to prove the checker bites.
    """
    import jax
    import jax.numpy as jnp

    from fedml_tpu.simulation.round_engine import build_round_core

    factory = core_factory or build_round_core
    core = factory(api, n_cohort=per, n_valid=per)

    def fused(state, cx_, cy_, cn_):
        # concrete key math: fold_in/split run eagerly on the real root key,
        # entering the jaxpr as constants — identical on the unfused side
        rkey = jax.random.fold_in(api.root_rng, round_idx)
        rngs = jax.random.split(rkey, per)
        cohort_idx = jnp.asarray(cohort, jnp.int32)
        new_state, metrics = core(state, cohort_idx, cx_, cy_, cn_, rngs,
                                  None, rkey)
        return new_state, metrics["train_loss"]

    return jax.make_jaxpr(fused)(state0, cx, cy, cn)


def trace_unfused(api, per: int, cohort, cx, cy, cn, state0,
                  round_idx: int = 0):
    """Canonical jaxpr of the REAL ``_train_round`` with host seams pinned
    (see module docstring). Restores every patched attribute."""
    import jax
    import jax.numpy as jnp

    from fedml_tpu.simulation import round_engine
    from fedml_tpu.simulation import sp_api as sp_mod

    saved_state = api._round_state()
    saved_sampling = api._client_sampling
    saved_gather = api._gather_cohort
    saved_mm = sp_mod._masked_mean
    # the cohort-index seam is pinned to the representation the fused
    # caller (_train_round_fused) ships: int32 on device — np-int64 host
    # indices lower through an extra device_put that is a seam artifact,
    # not round math
    cohort_dev = jnp.asarray(cohort, jnp.int32)

    def unfused(state, cx_, cy_, cn_):
        api._set_round_state(dict(state))
        api._gather_cohort = lambda _c: (cx_, cy_, cn_)
        out = api._train_round(round_idx)
        return api._round_state(), out["train_loss"]

    try:
        api._client_sampling = lambda _r: cohort_dev
        sp_mod._masked_mean = round_engine._masked_mean
        return jax.make_jaxpr(unfused)(state0, cx, cy, cn)
    finally:
        sp_mod._masked_mean = saved_mm
        api._client_sampling = saved_sampling
        api._gather_cohort = saved_gather
        api._set_round_state(saved_state)


def compare_round_paths(api, round_idx: int = 0,
                        core_factory: Optional[Callable] = None) -> Dict:
    """Trace both mirrors, canonicalize, diff. Returns the verdict dict
    that rides the JSON payload (one row per optimizer)."""
    per, cohort, cx, cy, cn, state0 = _example_round(api)
    closed_u = trace_unfused(api, per, cohort, cx, cy, cn, state0,
                             round_idx)
    closed_f = trace_fused(api, per, cohort, cx, cy, cn, state0,
                           round_idx, core_factory=core_factory)
    canon_u = canonicalize(closed_u)
    canon_f = canonicalize(closed_f)
    delta = diff_canonical(canon_u, canon_f)
    row: Dict[str, Any] = {
        "optimizer": str(api.opt_name),
        "equal": delta is None,
        "eqn_count_unfused": len(canon_u),
        "eqn_count_fused": len(canon_f),
        "diverges_at": None,
    }
    if delta is not None:
        i, lu, lf = delta
        row["diverges_at"] = i
        row["unfused_eqn"] = lu
        row["fused_eqn"] = lf
    return row


# ---------------------------------------------------------------------------
# the --equiv entry
# ---------------------------------------------------------------------------


def check_round_equivalence(repo_root: str) -> Tuple[List[Finding], List[Dict]]:
    """Compare the mirrors for FedAvg/FedOpt/SCAFFOLD; a divergence is a
    D006 finding naming the first differing canonical equation."""
    sys.path.insert(0, repo_root)
    try:
        from ..graftlint.runtime_check import _CONFIGS, _tiny_api
    except Exception as e:  # pragma: no cover - env without the package
        raise RuntimeError(
            f"graftrep --equiv unavailable: {type(e).__name__}: {e}"
        ) from e

    findings: List[Finding] = []
    report: List[Dict] = []
    for overrides in _CONFIGS:
        opt = overrides["federated_optimizer"]
        try:
            api = _tiny_api(overrides)
            row = compare_round_paths(api)
        except Exception as e:  # the tracer itself failing is exit 2
            raise RuntimeError(
                f"graftrep --equiv: tracing {opt} failed: "
                f"{type(e).__name__}: {e}"
            ) from e
        report.append(row)
        if not row["equal"]:
            findings.append(Finding(
                rule="D006", path=REL_ENGINE, line=1, col=0,
                message=(
                    f"fused mirror diverges from _train_round for {opt} at "
                    f"canonical eqn {row['diverges_at']}: unfused "
                    f"`{row['unfused_eqn']}` vs fused `{row['fused_eqn']}`"
                ),
                # one baseline key per (optimizer, divergence site)
                line_text=f"equiv::{opt}::{row['diverges_at']}",
            ))
    return findings, report
