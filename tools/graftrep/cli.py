"""graftrep CLI: ``python -m tools.graftrep [paths...]``.

Thin suite definition over the shared driver
(:mod:`tools.graftlint.clikit` — flags, baseline handling, rendering, and
the exit-code contract live there, shared with the three sibling suites).
Exit codes: 0 clean (after baseline + pragmas), 1 findings, 2 usage error
OR analyzer crash — that includes crashes inside the ``--equiv`` tracer.

Extra over the siblings:

- ``--equiv`` — trace the unfused ``FedAvgAPI._train_round`` trust chain
  (attack → defend → aggregate → DP) and ``round_engine.build_round_core``'s
  fused mirror under ``jax.make_jaxpr`` for FedAvg / FedOpt / SCAFFOLD,
  canonicalize both jaxprs, and diff. A divergence is a finding naming the
  first differing equation (imports jax; the default pass stays pure AST).
  The per-config verdicts ride the JSON payload under ``"equiv"``.
"""

from __future__ import annotations

import argparse
from typing import Dict, List, Optional, Tuple

from ..graftlint import clikit
from ..graftlint.findings import Finding
from .analyzer import DEFAULT_BASELINE_RELPATH, analyze_paths
from .findings import REP_RULES


def _add_arguments(p: argparse.ArgumentParser) -> None:
    p.add_argument("--equiv", action="store_true",
                   help="also prove fused/unfused round structural "
                        "equivalence: trace _train_round vs "
                        "build_round_core under jax.make_jaxpr for "
                        "FedAvg/FedOpt/SCAFFOLD, canonicalize, diff "
                        "(imports jax)")


def _analyze(args: argparse.Namespace,
             repo_root: str) -> Tuple[List[Finding], Dict]:
    findings = analyze_paths(args.paths, repo_root=repo_root)
    extra: Dict = {}
    if args.equiv:
        from .equiv import check_round_equivalence

        try:
            equiv_findings, report = check_round_equivalence(repo_root)
        except RuntimeError as e:
            raise clikit.SuiteUsageError(str(e)) from e
        findings = findings + equiv_findings
        extra["equiv"] = report
        if args.format != "json":
            for row in report:
                status = ("MATCH" if row["equal"]
                          else f"DIVERGED at eqn {row['diverges_at']}")
                print(f"equiv[{row['optimizer']}]: {status} "
                      f"({row['eqn_count_unfused']} unfused / "
                      f"{row['eqn_count_fused']} fused eqns)")
    return findings, extra


def main(argv: Optional[List[str]] = None) -> int:
    return clikit.run_suite(
        argv,
        tool="graftrep",
        description="static determinism & round-equivalence verification "
                    "of the trust pipeline: PRNG-key discipline, seed "
                    "provenance, unordered accumulation, dtype drift, "
                    "run-identity leaks; --equiv proves the fused round "
                    "mirror structurally equal to _train_round",
        rules=REP_RULES,
        analyze=_analyze,
        baseline_relpath=DEFAULT_BASELINE_RELPATH,
        add_arguments=_add_arguments,
    )


if __name__ == "__main__":
    raise SystemExit(main())
