"""Rule checkers D001–D005 over the analyzed function set.

The D-rules statically enforce the determinism discipline underneath the
repo's bitwise guarantees:

- **D001** PRNG-key reuse: a key is *dead* after a sampler consumed it.
  Deriving (``split``/``fold_in``) is unlimited; sampling is once-per-key.
  Dataflow-tracked through locals, aliases, closures (nested defs analyzed
  in source order with proper scoping) and helper calls (interprocedural
  "consumes-param" summaries).
- **D002** nondeterministic seed provenance: wall-clock / ``os.urandom`` /
  ``id()`` flowing into a PRNG seed position, any of those appearing inside
  traced code (they bake into trace constants that differ per process), and
  bare unseeded ``random``/``np.random`` module samplers anywhere.
- **D003** unordered iteration into accumulation: a ``set`` (or a shared
  attr-``dict`` populated in arrival order) feeding a float sum, a
  ``jnp``/``np`` reduction/stack, or a ``Message`` fan-out — float addition
  and wire bytes are both order-visible. (Dict-comprehension-over-set
  pytree construction is graftlint G003's, not repeated here.)
- **D004** dtype-promotion drift: explicit float64 / ``dtype=float`` casts
  and host ``np.*`` reductions inside traced or round/aggregation code —
  x86 promotes where TPU does not, killing cross-platform bitwise parity.
- **D005** run-identity leaks: wall-clock/hostname/pid flowing into
  ledger-committed state (``commit_round``/``ensure_meta`` payloads, the
  round-state/world dicts a resume replays) or gating send/aggregate/commit
  control flow.

Scope notes (documented limits, mirrored in docs/graftrep.md): D001 treats
nested function bodies as loop bodies (a ``lax.scan`` body runs per step);
``monotonic``/``perf_counter`` are durations, not run identity, and stay
out of D005; D003's dict half only fires on *attribute* dicts (shared,
arrival-ordered) — local literals are insertion-ordered by construction.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..graftlint.analyzer import (
    Analyzer,
    FuncInfo,
    ModuleInfo,
    _is_jaxish,
    _is_numpy,
    _walk_shallow,
    dotted,
)
from .findings import Finding

# jax.random functions that DERIVE new keys (unlimited uses of the key arg)
DERIVERS = {"split", "fold_in", "clone", "wrap_key_data"}
# jax.random functions with no key argument at all
KEYLESS = {"PRNGKey", "key", "key_data", "key_impl", "default_prng_impl"}

# module-level samplers on the stdlib `random` / `np.random` modules that
# draw from hidden, unseeded global state
BARE_SAMPLERS = {
    "random", "randint", "randrange", "uniform", "choice", "choices",
    "shuffle", "sample", "getrandbits", "gauss", "normalvariate",
    "betavariate", "expovariate", "vonmisesvariate", "paretovariate",
    "weibullvariate", "triangular", "lognormvariate", "rand", "randn",
    "normal", "permutation", "bytes", "standard_normal", "binomial",
    "poisson", "exponential", "gumbel",
}

# wall-clock / machine-identity producers. monotonic/perf_counter are
# durations — deliberately absent (timeout/flush logic is legitimate).
WALLCLOCK_FNS = {
    "time.time", "time.time_ns", "datetime.now", "datetime.utcnow",
    "datetime.datetime.now", "datetime.datetime.utcnow", "date.today",
    "datetime.date.today",
}
IDENTITY_FNS = {
    "socket.gethostname", "socket.getfqdn", "platform.node", "os.getpid",
    "os.getppid", "os.uname", "uuid.uuid1", "uuid.uuid4", "getpass.getuser",
    "os.getlogin",
}
ENTROPY_FNS = {
    "os.urandom", "secrets.token_bytes", "secrets.token_hex",
    "secrets.randbits", "secrets.randbelow", "secrets.token_urlsafe",
}

# seed sinks: (call-name-tail, positions of the seed-carrying args)
SEED_SINK_TAILS = {
    "PRNGKey": (0,), "key": (0,), "fold_in": (1,), "seed": (0,),
    "RandomState": (0,), "default_rng": (0,),
}

NP_REDUCERS = {"mean", "sum", "average", "var", "std", "prod", "dot",
               "cumsum", "nansum", "nanmean"}

SUMMISH_JNP = {"sum", "mean", "average", "stack", "concatenate", "prod",
               "asarray", "array"}

LEDGER_SINKS = {"commit_round", "ensure_meta"}
ROUND_STATE_FNS = ("_ledger_world", "ledger_identity", "_round_state",
                   "_ckpt_state")

_ROUNDISH = ("aggregate", "_train_round", "round_core", "superround",
             "_round_state")


def _mk(mod: ModuleInfo, rule: str, node: ast.AST, message: str) -> Finding:
    line = getattr(node, "lineno", 1)
    col = getattr(node, "col_offset", 0)
    return Finding(rule=rule, path=mod.rel, line=line, col=col,
                   message=message, line_text=mod.line_text(line))


def _jax_random_fn(mod: ModuleInfo, call: ast.Call) -> Optional[str]:
    """``jax.random.X(...)`` (any import spelling) → ``"X"``, else None."""
    ds = dotted(call.func)
    if ds is None:
        return None
    parts = ds.split(".")
    last = parts[-1]
    if len(parts) == 1:
        # from jax.random import split / fold_in / normal ...
        fi = mod.from_imports.get(last)
        if fi and fi[0] in ("jax.random", "jax._src.random"):
            return fi[1]
        return None
    head = parts[0]
    # jax.random.X / jrandom.X (import jax.random as jrandom) /
    # random.X (from jax import random)
    if head == "jax" and len(parts) >= 3 and parts[1] == "random":
        return last
    tgt = mod.imports.get(head, "")
    if tgt == "jax.random":
        return last
    fi = mod.from_imports.get(head)
    if fi and fi[0] == "jax" and fi[1] == "random":
        return last
    return None


def _key_arg(call: ast.Call, fname: str) -> Optional[ast.expr]:
    if fname in KEYLESS:
        return None
    if call.args:
        return call.args[0]
    for kw in call.keywords:
        if kw.arg == "key":
            return kw.value
    return None


def _np_random_fn(mod: ModuleInfo, call: ast.Call) -> Optional[str]:
    """``np.random.X(...)`` / stdlib ``random.X(...)`` → ``"X"``."""
    ds = dotted(call.func)
    if ds is None:
        return None
    parts = ds.split(".")
    if len(parts) < 2:
        return None
    head, last = parts[0], parts[-1]
    if _is_jaxish(mod, head):
        return None
    if len(parts) == 3 and parts[1] == "random" and _is_numpy(mod, head):
        return last
    if len(parts) == 2 and mod.imports.get(head, head) == "random" \
            and head == "random":
        return last
    if len(parts) == 2 and mod.imports.get(head, "") == "numpy.random":
        return last
    return None


def _source_call(mod: ModuleInfo, e: ast.expr,
                 names: Sequence[str]) -> Optional[str]:
    """``e`` is a call to one of the dotted ``names`` (suffix-matched on the
    last two components so ``dt.datetime.now()`` still resolves)."""
    if not isinstance(e, ast.Call):
        return None
    ds = dotted(e.func)
    if ds is None:
        return None
    for want in names:
        if ds == want or ds.endswith("." + want):
            return want
    return None


def _expr_contains(e: ast.expr, pred) -> Optional[ast.expr]:
    for node in ast.walk(e):
        if isinstance(node, ast.expr) and pred(node):
            return node
    return None


# ---------------------------------------------------------------------------
# D001: PRNG-key reuse
# ---------------------------------------------------------------------------


def build_key_summaries(modules: Dict[str, ModuleInfo],
                        lint: Analyzer) -> Dict[FuncInfo, Set[int]]:
    """Param positions each function CONSUMES as PRNG keys (a sampler uses
    them, directly or through one resolved call hop) — the interprocedural
    half of D001."""
    consumes: Dict[FuncInfo, Set[int]] = {}
    funcs = [(m, f) for m in modules.values()
             for f in m.funcs_by_node.values()]
    for _ in range(3):
        changed = False
        for mod, fi in funcs:
            pos_of = {name: i for i, name in enumerate(fi.params())}
            cur = consumes.setdefault(fi, set())
            for node in _walk_shallow(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                fname = _jax_random_fn(mod, node)
                if fname is not None:
                    if fname in DERIVERS or fname in KEYLESS:
                        continue
                    karg = _key_arg(node, fname)
                    if isinstance(karg, ast.Name) and karg.id in pos_of:
                        if pos_of[karg.id] not in cur:
                            cur.add(pos_of[karg.id])
                            changed = True
                    continue
                for t in lint.resolve_call_targets(mod, fi, node):
                    for p in consumes.get(t, ()):  # callee's consumed params
                        if p < len(node.args) and isinstance(
                                node.args[p], ast.Name):
                            name = node.args[p].id
                            if name in pos_of and pos_of[name] not in cur:
                                cur.add(pos_of[name])
                                changed = True
        if not changed:
            break
    return consumes


class _Key:
    __slots__ = ("id", "depth")
    _next = [0]

    def __init__(self, depth: int):
        _Key._next[0] += 1
        self.id = _Key._next[0]
        self.depth = depth


class _Binding:
    __slots__ = ("depth", "key")

    def __init__(self, depth: int):
        self.depth = depth
        self.key: Optional[_Key] = None


class _D001Checker:
    """Whole-closure-tree key analysis: runs on each TOP-LEVEL function and
    descends into nested defs in source order (a nested body is treated as
    a loop body — ``lax.scan``/``vmap`` bodies execute per step)."""

    def __init__(self, lint: Analyzer, mod: ModuleInfo, fi: FuncInfo,
                 summaries: Dict[FuncInfo, Set[int]]):
        self.lint = lint
        self.mod = mod
        self.root = fi
        self.summaries = summaries
        self.findings: List[Finding] = []
        self.scopes: List[Dict[str, _Binding]] = []
        self.attr_keys: Dict[str, _Key] = {}
        self.consumed: Dict[int, Tuple[int, str]] = {}  # key id -> (line, by)
        self.depth = 0
        self.cur_fi = fi

    # -- scoping ------------------------------------------------------------
    def _bind(self, name: str) -> None:
        self.scopes[-1][name] = _Binding(self.depth)

    def _binding(self, name: str) -> _Binding:
        for scope in reversed(self.scopes):
            if name in scope:
                return scope[name]
        b = _Binding(0)  # captured from beyond the tree (module global)
        self.scopes[0][name] = b
        return b

    def _key_of(self, e: ast.expr) -> Optional[_Key]:
        if isinstance(e, ast.Name):
            b = self._binding(e.id)
            if b.key is None:
                b.key = _Key(b.depth)
            return b.key
        if isinstance(e, ast.Attribute):
            path = dotted(e)
            if path is None:
                return None
            k = self.attr_keys.get(path)
            if k is None:
                k = self.attr_keys[path] = _Key(0)
            return k
        return None  # subscripts/calls: a fresh value each evaluation

    def _bind_target(self, t: ast.expr) -> None:
        if isinstance(t, ast.Name):
            self._bind(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._bind_target(e)
        elif isinstance(t, ast.Starred):
            self._bind_target(t.value)
        elif isinstance(t, ast.Attribute):
            path = dotted(t)
            if path:
                self.attr_keys.pop(path, None)

    # -- entry --------------------------------------------------------------
    def run(self) -> List[Finding]:
        self._enter_function(self.root)
        return self.findings

    def _enter_function(self, fi: FuncInfo) -> None:
        prev = self.cur_fi
        self.cur_fi = fi
        self.scopes.append({})
        a = fi.node.args
        for p in (a.posonlyargs + a.args + a.kwonlyargs):
            self._bind(p.arg)
        if a.vararg:
            self._bind(a.vararg.arg)
        if a.kwarg:
            self._bind(a.kwarg.arg)
        if isinstance(fi.node, ast.Lambda):
            self._visit_expr(fi.node.body)
        else:
            self._visit_block(fi.node.body)
        self.scopes.pop()
        self.cur_fi = prev

    # -- statements ----------------------------------------------------------
    def _visit_block(self, stmts: List[ast.stmt]) -> None:
        for s in stmts:
            self._visit_stmt(s)

    def _visit_stmt(self, s: ast.stmt) -> None:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._bind(s.name)
            fi = self.mod.funcs_by_node.get(id(s))
            if fi is not None:
                # a nested def is a latent loop body: bump depth so a
                # captured key consumed inside it reads as repeated use
                self.depth += 1
                self._enter_function(fi)
                self.depth -= 1
            return
        if isinstance(s, ast.ClassDef):
            self._bind(s.name)
            return
        if isinstance(s, ast.Assign):
            self._visit_expr(s.value)
            for t in s.targets:
                self._bind_target(t)
            return
        if isinstance(s, ast.AnnAssign):
            if s.value is not None:
                self._visit_expr(s.value)
                self._bind_target(s.target)
            return
        if isinstance(s, ast.AugAssign):
            self._visit_expr(s.value)
            if isinstance(s.target, ast.Name):
                self._bind(s.target.id)
            return
        if isinstance(s, (ast.For, ast.AsyncFor)):
            self._visit_expr(s.iter)
            self.depth += 1
            self._bind_target(s.target)
            self._visit_block(s.body)
            self.depth -= 1
            self._visit_block(s.orelse)
            return
        if isinstance(s, ast.While):
            self._visit_expr(s.test)
            self.depth += 1
            self._visit_block(s.body)
            self.depth -= 1
            self._visit_block(s.orelse)
            return
        if isinstance(s, ast.If):
            from ..graftlint.rules import _terminates

            self._visit_expr(s.test)
            before = dict(self.consumed)
            self._visit_block(s.body)
            # a branch that terminates (return/raise/...) contributes
            # nothing to the join — code after the If never follows it
            after_body = ({} if _terminates(s.body) else self.consumed)
            self.consumed = dict(before)
            self._visit_block(s.orelse)
            if s.orelse and _terminates(s.orelse):
                self.consumed = dict(before)
            merged = dict(self.consumed)  # may-consumed union of branches
            merged.update(after_body)
            self.consumed = merged
            return
        if isinstance(s, ast.Try):
            self._visit_block(s.body)
            for h in s.handlers:
                if h.name:
                    self._bind(h.name)
                self._visit_block(h.body)
            self._visit_block(s.orelse)
            self._visit_block(s.finalbody)
            return
        if isinstance(s, (ast.With, ast.AsyncWith)):
            for item in s.items:
                self._visit_expr(item.context_expr)
                if item.optional_vars is not None:
                    self._bind_target(item.optional_vars)
            self._visit_block(s.body)
            return
        for child in ast.iter_child_nodes(s):
            if isinstance(child, ast.expr):
                self._visit_expr(child)
            elif isinstance(child, ast.stmt):
                self._visit_stmt(child)

    # -- expressions ---------------------------------------------------------
    def _visit_expr(self, e: Optional[ast.expr]) -> None:
        if e is None:
            return
        if isinstance(e, ast.Call):
            self._visit_call(e)
            return
        if isinstance(e, ast.Lambda):
            fi = self.mod.funcs_by_node.get(id(e))
            if fi is not None:
                self.depth += 1
                self._enter_function(fi)
                self.depth -= 1
            return
        if isinstance(e, (ast.ListComp, ast.SetComp, ast.DictComp,
                          ast.GeneratorExp)):
            for gen in e.generators:
                self._visit_expr(gen.iter)
            self.depth += 1
            self.scopes.append({})
            for gen in e.generators:
                self._bind_target(gen.target)
                for cond in gen.ifs:
                    self._visit_expr(cond)
            if isinstance(e, ast.DictComp):
                self._visit_expr(e.key)
                self._visit_expr(e.value)
            else:
                self._visit_expr(e.elt)
            self.scopes.pop()
            self.depth -= 1
            return
        for child in ast.iter_child_nodes(e):
            if isinstance(child, ast.expr):
                self._visit_expr(child)

    def _visit_call(self, call: ast.Call) -> None:
        for a in call.args:
            self._visit_expr(a)
        for kw in call.keywords:
            self._visit_expr(kw.value)
        if not isinstance(call.func, (ast.Name, ast.Attribute)):
            self._visit_expr(call.func)

        fname = _jax_random_fn(self.mod, call)
        if fname is not None:
            karg = _key_arg(call, fname)
            if karg is None:
                return
            key = self._key_of(karg)
            if key is None:
                return
            label = dotted(karg) or "<key>"
            if fname in DERIVERS:
                self._check_dead(key, call, label,
                                 f"jax.random.{fname}", consuming=False)
            else:
                self._consume(key, call, label, f"jax.random.{fname}")
            return

        # interprocedural: helper(key) where the helper's summary says the
        # param position reaches a sampler
        for t in self.lint.resolve_call_targets(self.mod, self.cur_fi, call):
            for p in self.summaries.get(t, ()):
                if p < len(call.args):
                    key = self._key_of(call.args[p])
                    if key is not None:
                        label = dotted(call.args[p]) or "<key>"
                        self._consume(key, call, label,
                                      f"{dotted(call.func) or t.name}()")

    def _check_dead(self, key: _Key, call: ast.Call, label: str,
                    by: str, consuming: bool) -> None:
        prior = self.consumed.get(key.id)
        if prior is not None:
            line, consumer = prior
            verb = "consumed again by" if consuming else "fed to"
            self.findings.append(_mk(
                self.mod, "D001", call,
                f"key `{label}` was consumed by {consumer} (line {line}) "
                f"and is {verb} {by} — a consumed key is dead; derive "
                "subkeys BEFORE sampling",
            ))

    def _consume(self, key: _Key, call: ast.Call, label: str,
                 by: str) -> None:
        prior = self.consumed.get(key.id)
        if prior is not None:
            self._check_dead(key, call, label, by, consuming=True)
            return
        if key.depth < self.depth:
            self.findings.append(_mk(
                self.mod, "D001", call,
                f"key `{label}` defined outside this loop/closure is "
                f"consumed by {by} inside it — every iteration draws the "
                "same stream; fold the loop index in first",
            ))
        self.consumed[key.id] = (call.lineno, by)


# ---------------------------------------------------------------------------
# D002: nondeterministic seed provenance
# ---------------------------------------------------------------------------

_D002_SOURCES = tuple(WALLCLOCK_FNS) + tuple(ENTROPY_FNS) + (
    "uuid.uuid4", "uuid.uuid1")


class _D002Checker:
    def __init__(self, lint: Analyzer, mod: ModuleInfo, fi: FuncInfo):
        self.lint = lint
        self.mod = mod
        self.fi = fi
        self.findings: List[Finding] = []
        self.tainted: Dict[str, str] = {}  # name -> source description

    def _source_of(self, e: ast.expr) -> Optional[str]:
        """A nondeterministic expression (source call, id(), tainted name)
        anywhere inside ``e``."""
        for node in ast.walk(e):
            if not isinstance(node, ast.expr):
                continue
            src = _source_call(self.mod, node, _D002_SOURCES)
            if src is not None:
                return src
            if (isinstance(node, ast.Call) and dotted(node.func) == "id"
                    and node.args):
                return "id()"
            if isinstance(node, ast.Name) and node.id in self.tainted:
                return self.tainted[node.id]
        return None

    def run(self) -> List[Finding]:
        body = ([ast.Expr(self.fi.node.body)]
                if isinstance(self.fi.node, ast.Lambda)
                else self.fi.node.body)
        self._record = False
        self._visit(body)  # pass 1: taint fixpoint across loops
        self._record = True
        self._visit(body)
        return self.findings

    def _visit(self, stmts: List[ast.stmt]) -> None:
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            if isinstance(s, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                value = getattr(s, "value", None)
                if value is not None:
                    self._check_exprs(value)
                    src = self._source_of(value) if value is not None else None
                    targets = (s.targets if isinstance(s, ast.Assign)
                               else [s.target])
                    for t in targets:
                        if isinstance(t, ast.Name):
                            if src is not None:
                                self.tainted[t.id] = src
                            elif not isinstance(s, ast.AugAssign):
                                self.tainted.pop(t.id, None)
                continue
            for child in ast.iter_child_nodes(s):
                if isinstance(child, ast.expr):
                    self._check_exprs(child)
                elif isinstance(child, ast.stmt):
                    self._visit([child])

    def _check_exprs(self, e: ast.expr) -> None:
        if not self._record:
            return
        for node in ast.walk(e):
            if not isinstance(node, ast.Call):
                continue
            self._check_call(node)

    def _check_call(self, call: ast.Call) -> None:
        # bare unseeded module samplers: nondeterministic anywhere
        npfn = _np_random_fn(self.mod, call)
        if npfn in BARE_SAMPLERS:
            self.findings.append(_mk(
                self.mod, "D002", call,
                f"unseeded module-level `{dotted(call.func)}` draws from "
                "hidden global state — use a seeded np.random.RandomState/"
                "default_rng (or jax.random with a config-derived key)",
            ))
            return
        # seed sinks fed from a nondeterministic source
        ds = dotted(call.func)
        tail = ds.split(".")[-1] if ds else ""
        positions = SEED_SINK_TAILS.get(tail)
        is_seed_sink = positions is not None and (
            _jax_random_fn(self.mod, call) in ("PRNGKey", "key", "fold_in")
            or (npfn in ("seed", "RandomState", "default_rng"))
            or (ds == "random.seed"
                and not _is_jaxish(self.mod, "random"))
        )
        if is_seed_sink:
            for p in positions:
                if p < len(call.args):
                    src = self._source_of(call.args[p])
                    if src is not None:
                        self.findings.append(_mk(
                            self.mod, "D002", call,
                            f"PRNG seeded from `{src}` — the trajectory "
                            "can never be replayed; derive seeds from "
                            "config (random_seed, round index, rank)",
                        ))
                        break
        # inside traced code, a wall-clock/entropy read bakes a
        # per-process constant into the jaxpr
        if self.fi.traced:
            src = _source_call(self.mod, call, _D002_SOURCES)
            if src is not None:
                self.findings.append(_mk(
                    self.mod, "D002", call,
                    f"`{src}` inside traced `{self.fi.qualname}` bakes a "
                    "per-process constant into the compiled program — two "
                    "hosts trace two different programs",
                ))


# ---------------------------------------------------------------------------
# D003: unordered iteration into accumulation
# ---------------------------------------------------------------------------


def _attr_container_kinds(mod: ModuleInfo) -> Dict[str, str]:
    """self-attributes assigned ``set()``/``{}``/``dict()`` anywhere in the
    module's classes → "set" | "dict" (shared, arrival-ordered state)."""
    kinds: Dict[str, str] = {}
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        t = node.targets[0]
        if not (isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name)
                and t.value.id == "self"):
            continue
        v = node.value
        if isinstance(v, ast.Set) or (
                isinstance(v, ast.Call) and dotted(v.func) in ("set",
                                                               "frozenset")):
            kinds[t.attr] = "set"
        elif isinstance(v, ast.Dict) and not v.keys or (
                isinstance(v, ast.Call) and dotted(v.func) == "dict"
                and not v.args and not v.keywords):
            kinds.setdefault(t.attr, "dict")
    return kinds


class _D003Checker:
    def __init__(self, lint: Analyzer, mod: ModuleInfo, fi: FuncInfo,
                 attr_kinds: Dict[str, str]):
        self.mod = mod
        self.fi = fi
        self.attr_kinds = attr_kinds
        self.set_locals: Set[str] = set()
        self.findings: List[Finding] = []

    # -- classification -----------------------------------------------------
    def _unordered(self, e: ast.expr) -> Optional[str]:
        """Why ``e`` iterates in unspecified order, or None."""
        if isinstance(e, (ast.Set, ast.SetComp)):
            return "a set"
        if isinstance(e, ast.Call):
            ds = dotted(e.func)
            if ds in ("set", "frozenset"):
                return "a set"
            if ds in ("list", "tuple", "iter", "reversed") and e.args:
                return self._unordered(e.args[0])
            if isinstance(e.func, ast.Attribute):
                if e.func.attr in ("union", "intersection", "difference",
                                  "symmetric_difference"):
                    inner = self._unordered(e.func.value)
                    if inner:
                        return "a set"
                if e.func.attr in ("keys", "values", "items"):
                    recv = e.func.value
                    if (isinstance(recv, ast.Attribute)
                            and isinstance(recv.value, ast.Name)
                            and recv.value.id == "self"
                            and self.attr_kinds.get(recv.attr) == "dict"):
                        return (f"shared dict `self.{recv.attr}` "
                                "(arrival-ordered)")
            return None
        if isinstance(e, ast.Name) and e.id in self.set_locals:
            return "a set"
        if isinstance(e, ast.BinOp):
            left = self._unordered(e.left)
            right = self._unordered(e.right)
            if left == "a set" or right == "a set":
                return "a set"
            return None
        if (isinstance(e, ast.Attribute) and isinstance(e.value, ast.Name)
                and e.value.id == "self"):
            kind = self.attr_kinds.get(e.attr)
            if kind == "set":
                return f"shared set `self.{e.attr}`"
            if kind == "dict":
                return f"shared dict `self.{e.attr}` (arrival-ordered)"
        return None

    # -- entry ---------------------------------------------------------------
    def run(self) -> List[Finding]:
        if isinstance(self.fi.node, ast.Lambda):
            return []
        self._scan_set_locals(self.fi.node)
        for node in _walk_shallow(self.fi.node):
            if isinstance(node, (ast.For, ast.AsyncFor)):
                why = self._unordered(node.iter)
                if why:
                    self._check_loop_body(node, why)
            elif isinstance(node, ast.Call):
                self._check_summish(node)
        return self.findings

    def _scan_set_locals(self, root: ast.AST) -> None:
        for _ in range(2):  # one extra pass for chained set locals
            for node in _walk_shallow(root):
                if (isinstance(node, ast.Assign) and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)):
                    name = node.targets[0].id
                    why = self._unordered(node.value)
                    if why is not None and "set" in why:
                        self.set_locals.add(name)
                    else:
                        self.set_locals.discard(name)

    # -- sinks ---------------------------------------------------------------
    def _check_loop_body(self, loop: ast.For, why: str) -> None:
        for node in ast.walk(loop):
            if isinstance(node, ast.AugAssign) and isinstance(
                    node.op, ast.Add):
                if isinstance(node.value, ast.Constant) and isinstance(
                        node.value.value, int):
                    continue  # integer counting commutes
                self.findings.append(_mk(
                    self.mod, "D003", node,
                    f"accumulation inside iteration over {why} — float "
                    "addition is order-visible and set order is "
                    "process-dependent; iterate sorted(...)",
                ))
                return
            if isinstance(node, ast.Call):
                ds = dotted(node.func)
                tail = ds.split(".")[-1] if ds else ""
                if tail == "send_message" or ds == "Message" or (
                        ds or "").endswith(".Message"):
                    self.findings.append(_mk(
                        self.mod, "D003", node,
                        f"message fan-out inside iteration over {why} — "
                        "send order is wire-visible (retry/dedup windows, "
                        "payload digests); iterate sorted(...)",
                    ))
                    return

    def _check_summish(self, call: ast.Call) -> None:
        ds = dotted(call.func)
        if ds is None:
            return
        parts = ds.split(".")
        tail = parts[-1]
        is_builtin_sum = ds == "sum"
        is_np_sum = (len(parts) > 1 and tail in SUMMISH_JNP
                     and (_is_jaxish(self.mod, parts[0])
                          or _is_numpy(self.mod, parts[0])))
        is_stack_trees = tail == "stack_trees"
        if not (is_builtin_sum or is_np_sum or is_stack_trees):
            return
        for a in call.args:
            comp = a if isinstance(a, (ast.GeneratorExp, ast.ListComp)) \
                else None
            if comp is None:
                why = self._unordered(a)
                if why and not is_builtin_sum:
                    self.findings.append(_mk(
                        self.mod, "D003", call,
                        f"`{ds}` over {why} — element order is "
                        "process-dependent; sort first",
                    ))
                continue
            if is_builtin_sum and isinstance(comp.elt, ast.Constant):
                continue  # sum(1 for ...) counts, order-free
            for gen in comp.generators:
                why = self._unordered(gen.iter)
                if why:
                    self.findings.append(_mk(
                        self.mod, "D003", call,
                        f"`{ds}` accumulates over {why} — float addition/"
                        "stacking is order-visible; iterate sorted(...)",
                    ))
                    return


# ---------------------------------------------------------------------------
# D004: dtype-promotion drift
# ---------------------------------------------------------------------------


def _is_float64_expr(mod: ModuleInfo, e: ast.expr) -> bool:
    if isinstance(e, ast.Name) and e.id == "float":
        return True
    if isinstance(e, ast.Constant) and e.value in ("float64", "double"):
        return True
    ds = dotted(e)
    if ds is None:
        return False
    parts = ds.split(".")
    return parts[-1] in ("float64", "double") and (
        _is_numpy(mod, parts[0]) or _is_jaxish(mod, parts[0]))


class _D004Checker:
    def __init__(self, lint: Analyzer, mod: ModuleInfo, fi: FuncInfo):
        self.mod = mod
        self.fi = fi
        self.findings: List[Finding] = []

    def run(self) -> List[Finding]:
        where = (f"traced `{self.fi.qualname}`" if self.fi.traced
                 else f"round/aggregation code `{self.fi.qualname}`")
        for node in _walk_shallow(self.fi.node):
            if not isinstance(node, ast.Call):
                continue
            ds = dotted(node.func)
            parts = ds.split(".") if ds else []
            # explicit float64 constructor: np.float64(x) / jnp.float64(x)
            if parts and parts[-1] in ("float64", "double") and len(parts) > 1 \
                    and (_is_numpy(self.mod, parts[0])
                         or _is_jaxish(self.mod, parts[0])):
                self.findings.append(_mk(
                    self.mod, "D004", node,
                    f"`{ds}(...)` in {where} promotes to float64 — "
                    "cross-platform bitwise parity needs one explicit "
                    "narrow dtype",
                ))
                continue
            # .astype(float) / .astype("float64") / .astype(np.float64)
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "astype" and node.args
                    and _is_float64_expr(self.mod, node.args[0])):
                self.findings.append(_mk(
                    self.mod, "D004", node,
                    f".astype(float64) in {where} — weak Python `float` "
                    "means float64; name the narrow dtype explicitly",
                ))
                continue
            # dtype=float / dtype="float64" / dtype=np.float64 keywords
            for kw in node.keywords:
                if kw.arg == "dtype" and _is_float64_expr(self.mod,
                                                          kw.value):
                    self.findings.append(_mk(
                        self.mod, "D004", kw.value,
                        f"dtype=float64 in {where} — x64 math diverges "
                        "bitwise from the f32 path on other platforms",
                    ))
            # numpy reductions inside TRACED code run on host at trace time
            # with float64 accumulators
            if (self.fi.traced and len(parts) > 1
                    and parts[-1] in NP_REDUCERS
                    and _is_numpy(self.mod, parts[0])):
                self.findings.append(_mk(
                    self.mod, "D004", node,
                    f"`{ds}` inside {where} runs on host with a float64 "
                    "accumulator at trace time — use the jnp twin",
                ))
        return self.findings


# ---------------------------------------------------------------------------
# D005: run-identity leaks
# ---------------------------------------------------------------------------

_D005_SOURCES = tuple(WALLCLOCK_FNS) + tuple(IDENTITY_FNS)


class _D005Checker:
    def __init__(self, lint: Analyzer, mod: ModuleInfo, fi: FuncInfo):
        self.mod = mod
        self.fi = fi
        self.findings: List[Finding] = []
        self.tainted: Dict[str, str] = {}

    def _source_of(self, e: ast.expr) -> Optional[str]:
        for node in ast.walk(e):
            if not isinstance(node, ast.expr):
                continue
            src = _source_call(self.mod, node, _D005_SOURCES)
            if src is not None:
                return src
            if isinstance(node, ast.Name) and node.id in self.tainted:
                return self.tainted[node.id]
        return None

    def run(self) -> List[Finding]:
        if isinstance(self.fi.node, ast.Lambda):
            return []
        # taint pass (document order, two rounds for loops)
        for _ in range(2):
            for node in _walk_shallow(self.fi.node):
                if (isinstance(node, ast.Assign) and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)):
                    src = self._source_of(node.value)
                    if src is not None:
                        self.tainted[node.targets[0].id] = src
        state_fn = any(tok in self.fi.name for tok in ROUND_STATE_FNS)
        for node in _walk_shallow(self.fi.node):
            if isinstance(node, ast.Call):
                self._check_ledger_sink(node)
            if state_fn and isinstance(node, ast.Return) \
                    and node.value is not None:
                src = self._source_of(node.value)
                if src is not None:
                    self.findings.append(_mk(
                        self.mod, "D005", node,
                        f"`{src}` flows into the state `{self.fi.qualname}` "
                        "returns — resumed runs replay this dict and can "
                        "never reproduce it bitwise",
                    ))
            if isinstance(node, ast.If):
                self._check_control(node)
        return self.findings

    def _check_ledger_sink(self, call: ast.Call) -> None:
        ds = dotted(call.func)
        tail = ds.split(".")[-1] if ds else ""
        if tail not in LEDGER_SINKS:
            return
        for e in list(call.args) + [kw.value for kw in call.keywords]:
            src = self._source_of(e)
            if src is not None:
                self.findings.append(_mk(
                    self.mod, "D005", call,
                    f"`{src}` flows into ledger commit `{tail}` — "
                    "committed round state must be a pure function of "
                    "(seed, config, round)",
                ))
                return

    def _check_control(self, node: ast.If) -> None:
        src = self._source_of(node.test)
        if src is None:
            return
        for inner in ast.walk(node):
            if isinstance(inner, ast.Call):
                ds = dotted(inner.func) or ""
                tail = ds.split(".")[-1]
                if tail in ("send_message", "commit_round") or \
                        "aggregate" in tail or "dispatch" in tail:
                    self.findings.append(_mk(
                        self.mod, "D005", node,
                        f"`{src}` gates `{tail}` — wall-clock/host "
                        "identity steering the round path makes runs "
                        "unreplayable (telemetry it instead)",
                    ))
                    return


# ---------------------------------------------------------------------------
# Entry
# ---------------------------------------------------------------------------


def check_determinism(modules: Dict[str, ModuleInfo],
                      lint: Analyzer) -> List[Finding]:
    summaries = build_key_summaries(modules, lint)
    findings: List[Finding] = []
    for mod in modules.values():
        attr_kinds = _attr_container_kinds(mod)
        for fi in mod.funcs_by_node.values():
            if fi.parent is None:
                findings += _D001Checker(lint, mod, fi, summaries).run()
            findings += _D002Checker(lint, mod, fi).run()
            findings += _D003Checker(lint, mod, fi, attr_kinds).run()
            if fi.traced or any(tok in fi.qualname for tok in _ROUNDISH):
                findings += _D004Checker(lint, mod, fi).run()
            findings += _D005Checker(lint, mod, fi).run()
    return findings
