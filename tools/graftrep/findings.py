"""graftrep rule registry (D001–D005), merged into the shared graftlint
Finding infrastructure so all four suites render/baseline/JSON identically.

The D-rules statically enforce the repo's determinism discipline — the
precondition for every bitwise guarantee the runtime parity tests pin
(kill/restart parity, sync≡async at alpha=0, delta-shipped ≡ full
broadcast). ``--equiv`` (see :mod:`equiv`) closes the other half: the fused
round mirror must stay structurally identical to the unfused reference.
"""

from __future__ import annotations

from typing import Dict, Tuple

from ..graftlint.findings import Finding, register_rules

# rule id -> (title, autofix hint)
REP_RULES: Dict[str, Tuple[str, str]] = {
    "D001": (
        "prng-key-reuse",
        "a key is dead once a sampler consumed it: derive per-use subkeys "
        "FIRST (`k_a, k_b = jax.random.split(k)` or "
        "`jax.random.fold_in(k, tag)` with distinct tags), then consume "
        "each subkey exactly once — reuse correlates streams that every "
        "parity proof assumes independent",
    ),
    "D002": (
        "nondeterministic-seed-provenance",
        "seed PRNGs from config only (args.random_seed, round index, rank): "
        "wall-clock, os.urandom, id() and unseeded random/np.random make "
        "the trajectory unreproducible — a kill/restart can never be "
        "bitwise-replayed from a seed nobody recorded",
    ),
    "D003": (
        "unordered-iteration-into-accumulation",
        "iterate `sorted(...)` (or a list with pinned order) before feeding "
        "a float sum, pytree build, or message fan-out — set order is "
        "process-dependent (hash randomization) and float addition does "
        "not commute bitwise",
    ),
    "D004": (
        "dtype-promotion-drift",
        "keep traced math in the model dtype: np.* reductions and "
        "float64/`dtype=float` casts inside round/aggregation code promote "
        "through float64 on some platforms and not others, breaking "
        "cross-platform bitwise parity — use jnp with an explicit narrow "
        "dtype",
    ),
    "D005": (
        "run-identity-leak",
        "ledger-committed round state must be a pure function of "
        "(seed, config, round): route wall-clock/hostname/pid to logs or "
        "telemetry, never into commit_round/ensure_meta payloads or the "
        "round-state dicts a resume replays",
    ),
    "D006": (
        "fused-unfused-round-divergence",
        "the fused round mirror (round_engine.build_round_core) drifted "
        "from the unfused reference (_train_round): re-align the mirror at "
        "the named equation — or better, extract the shared chain into one "
        "function both paths consume (the ROADMAP trust-pipeline refactor)",
    ),
}

register_rules(REP_RULES)

__all__ = ["Finding", "REP_RULES"]
