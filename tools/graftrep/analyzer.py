"""graftrep entry: scan → graftlint facts → D-rules → pragmas.

Mirrors :func:`tools.graftshard.analyzer.analyze_paths_with_model`, with
graftrep's own pragma marker (``# graftrep: disable=D001``) and baseline
file (``tools/graftrep/baseline.json``). The default pass is pure AST —
no jax import — so the tree gate stays sub-second; ``--equiv``
(:mod:`equiv`) opts into jax.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

from ..graftlint.analyzer import Analyzer, collect_files, load_modules
from ..graftlint.baseline import find_repo_root
from ..graftlint.pragmas import is_suppressed, parse_pragmas
from .findings import Finding
from .rules import check_determinism

PRAGMA_TOOL = "graftrep"
DEFAULT_BASELINE_RELPATH = os.path.join("tools", "graftrep", "baseline.json")


def default_baseline_path(repo_root: str) -> str:
    return os.path.join(repo_root, DEFAULT_BASELINE_RELPATH)


def analyze_paths(paths: Sequence[str],
                  repo_root: Optional[str] = None) -> List[Finding]:
    """Analyze files/dirs → pragma-filtered findings.

    The baseline is NOT applied here — that's the CLI/caller's job, like
    the sibling suites.
    """
    if repo_root is None:
        repo_root = find_repo_root(paths[0] if paths else os.getcwd())
    files = collect_files(paths)
    modules = load_modules(files, repo_root)
    # graftlint's jit call graph marks the traced set — "traced code" means
    # the same thing to the D-rules as it does to the G-rules
    lint = Analyzer(modules)
    lint.compute_facts()
    lint.propagate()
    findings = check_determinism(modules, lint)

    out: List[Finding] = []
    pragma_cache: Dict[str, Dict] = {}
    mods_by_rel = {m.rel: m for m in modules.values()}
    for f in findings:
        mod = mods_by_rel.get(f.path)
        if mod is not None:
            pragmas = pragma_cache.setdefault(
                f.path, parse_pragmas(mod.source, tool=PRAGMA_TOOL))
            if is_suppressed(pragmas, f.rule, f.line):
                continue
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out
