"""graftrep: static determinism & round-equivalence verification.

The fourth static-analysis suite (after graftlint/graftproto/graftshard),
on the same shared driver (:mod:`tools.graftlint.clikit`):

- **D-rules** (pure AST, no jax import): PRNG-key discipline (D001),
  seed provenance (D002), unordered iteration into accumulation (D003),
  dtype-promotion drift (D004), run-identity leaks into ledger state
  (D005) — the static enforcement of every bitwise guarantee the parity
  tests pin at runtime.
- **--equiv** (imports jax): traces the unfused ``FedAvgAPI._train_round``
  trust chain and ``round_engine.build_round_core``'s fused mirror under
  ``jax.make_jaxpr``, canonicalizes both jaxprs, and diffs them — a
  drifted mirror is a lint finding naming the first diverging equation,
  not a silent wait for a parity test to notice.

Entry points: ``python -m tools.graftrep`` / ``fedml_tpu lint --rep``.
"""

from .analyzer import analyze_paths
from .findings import REP_RULES, Finding

__all__ = ["analyze_paths", "Finding", "REP_RULES"]
