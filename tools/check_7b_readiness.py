"""7B readiness proof, settled by the REAL TPU compiler (VERDICT r3 next #2).

``TransformerConfig.llama2_7b()``'s full fsdp-sharded train step (forward,
backward, AdamW update, splash attention shard_mapped over the mesh) is
AOT-compiled against genuine v5e TPU topologies via
``jax.experimental.topologies`` — no chips needed, the machine's TPU
compiler targets the topology directly. The compiler's own
``memory_analysis()`` is the verdict: per-chip HBM = resident arguments
(params + optimizer + batch) + temp buffers (activations + workspace),
compared against the v5e chip budget. An analytic budget table is printed
alongside and must AGREE with the compiler (the r3 artifact's 383 GiB
XLA:CPU temp figure is gone — the CPU backend's layout/fusion decisions are
meaningless for TPU HBM, which is exactly why the TPU compiler is asked).

Usage:  python tools/check_7b_readiness.py [--rows v5e:8,v5p:32]
                                           [--seq-len 2048]
Needs the TPU plugin (run under the default axon env). Prints one JSON line
at the end; exit 0 = every compiled config's compiler-reported HBM fits its
chip.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

GiB = 1024**3
CHIP_HBM = {"v5e": 16 * GiB, "v5p": 95 * GiB}
# slice topologies by (chip, count): v5e is 2-D, v5p is 3-D
TOPO = {
    ("v5e", 4): "v5e:2x2", ("v5e", 8): "v5e:2x4",
    ("v5e", 16): "v5e:4x4", ("v5e", 32): "v5e:4x8",
    ("v5p", 4): "v5p:2x2x1", ("v5p", 8): "v5p:2x2x2",
    ("v5p", 16): "v5p:2x4x2", ("v5p", 32): "v5p:2x4x4",
}


def parse_rows(spec: str):
    """"v5e:8,v5p:32" → [("v5e", 8), ...] with a helpful error."""
    rows = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        chip, _, n = part.partition(":")
        try:
            key = (chip, int(n))
        except ValueError:
            key = None
        if key not in TOPO:
            supported = ", ".join(f"{c}:{k}" for c, k in sorted(TOPO))
            raise SystemExit(
                f"unsupported row {part!r}; supported: {supported}"
            )
        rows.append(key)
    if not rows:
        raise SystemExit("no rows requested")
    return rows


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", default="v5e:8,v5e:16,v5p:32",
                    help="comma list of <chip>:<fsdp> rows to AOT-compile "
                         "(v5p:32 = the BASELINE north-star slice)")
    ap.add_argument("--batch-per-shard", type=int, default=1)
    ap.add_argument("--seq-len", type=int, default=2048)
    # bf16 first moment (make_optimizer docstring: "on a single 16 GiB chip
    # the difference between spilling and staying resident") — the compiler
    # run below proves it IS the difference at fsdp=8 on v5e
    ap.add_argument("--mu-dtype", default="bfloat16",
                    choices=["float32", "bfloat16"])
    ap.add_argument("--out", default=os.path.join(REPO,
                                                  "SEVENB_READINESS.json"))
    a = ap.parse_args()

    import jax
    import jax.numpy as jnp
    from jax.experimental import topologies
    from jax.sharding import NamedSharding, PartitionSpec as P

    from fedml_tpu.parallel.context import mesh_context
    from fedml_tpu.parallel.pipeline import _opt_state_specs
    from fedml_tpu.parallel.sharding import make_mesh
    from fedml_tpu.parallel.train_step import (
        CheetahTrainer,
        TrainState,
        make_optimizer,
    )
    from fedml_tpu.parallel.transformer import TransformerConfig

    cfg = dataclasses.replace(
        TransformerConfig.llama2_7b(), max_seq_len=a.seq_len
    )

    def tree_bytes(tree):
        return sum(
            int(x.size) * jnp.dtype(x.dtype).itemsize
            for x in jax.tree.leaves(tree)
        )

    def compile_for(chip: str, n_chips: int) -> dict:
        """AOT-compile the fsdp=n_chips step against a chip topology and
        return the compiler's per-chip memory verdict."""
        topo = topologies.get_topology_desc(
            platform="tpu", topology_name=TOPO[(chip, n_chips)]
        )
        mesh = make_mesh({"fsdp": n_chips}, devices=list(topo.devices))
        trainer = CheetahTrainer(
            cfg, mesh,
            optimizer=make_optimizer(3e-4, mu_dtype=jnp.dtype(a.mu_dtype)),
        )
        params_abs = jax.eval_shape(
            trainer._init_raw, jax.random.PRNGKey(0)
        )["params"]
        opt_abs = jax.eval_shape(trainer.opt.init, params_abs)
        p_spec = jax.tree.map(
            lambda s: s.spec, trainer.param_shardings,
            is_leaf=lambda x: isinstance(x, NamedSharding),
        )
        o_spec = _opt_state_specs(p_spec, opt_abs)

        def sds(al, spec):
            return jax.ShapeDtypeStruct(
                al.shape, al.dtype, sharding=NamedSharding(mesh, spec)
            )

        state_abs = TrainState(
            step=sds(jax.ShapeDtypeStruct((), jnp.int32), P()),
            params=jax.tree.map(sds, params_abs, p_spec),
            opt_state=jax.tree.map(
                sds, opt_abs, o_spec,
                is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct),
            ),
        )
        B = a.batch_per_shard * n_chips
        tok = jax.ShapeDtypeStruct(
            (B, a.seq_len), jnp.int32, sharding=trainer._batch_shard
        )
        t0 = time.time()
        with mesh, mesh_context(mesh):
            compiled = trainer._step_jit.lower(state_abs, tok, tok).compile()
        secs = round(time.time() - t0, 1)
        ma = compiled.memory_analysis()
        args_b = int(ma.argument_size_in_bytes)
        temp_b = int(ma.temp_size_in_bytes)
        out_b = int(ma.output_size_in_bytes)
        alias_b = int(ma.alias_size_in_bytes)
        # peak per-chip HBM: resident inputs + temps + any non-aliased
        # outputs (donated state aliases its argument buffers)
        hbm = args_b + temp_b + max(out_b - alias_b, 0)
        n_params = sum(int(x.size) for x in jax.tree.leaves(params_abs))
        state_bytes = tree_bytes(params_abs) + tree_bytes(opt_abs)
        analytic_args = state_bytes / n_chips \
            + B * a.seq_len * 8 / n_chips  # tokens+mask int32, batch-sharded
        row = {
            "chip": chip,
            "fsdp": n_chips,
            "topology": TOPO[(chip, n_chips)],
            "compile_s": secs,
            "params_b": round(n_params / 1e9, 3),
            "compiler_args_gib": round(args_b / GiB, 2),
            "compiler_temp_gib": round(temp_b / GiB, 2),
            "compiler_hbm_gib_per_chip": round(hbm / GiB, 2),
            "analytic_state_gib_per_chip": round(analytic_args / GiB, 2),
            "agree": abs(args_b - analytic_args) / analytic_args < 0.05,
            "fits": hbm < CHIP_HBM[chip] * 0.95,
        }
        print(json.dumps(row))
        return row

    requested = parse_rows(a.rows)
    rows = []
    for chip, n in requested:
        rows.append(compile_for(chip, n))
        out = {
            "model": "llama2_7b",
            "seq_len": a.seq_len,
            "batch_per_shard": a.batch_per_shard,
            "mu_dtype": a.mu_dtype,
            "remat": cfg.remat,
            "source": "TPU compiler memory_analysis via AOT topologies",
            "rows_requested": [f"{c}:{k}" for c, k in requested],
            # a partial artifact (crash mid-list) must be distinguishable
            # from a complete run: fits/agree only cover finished rows
            "complete": len(rows) == len(requested),
            "rows": rows,
            "fits": all(r["fits"] for r in rows),
            "analytic_agrees_with_compiler": all(r["agree"] for r in rows),
        }
        # write after EVERY row: each costs minutes of TPU AOT compile, and
        # a crash mid-list must not discard finished rows
        with open(a.out, "w") as f:
            json.dump(out, f, indent=2)
    print(json.dumps(out))
    sys.exit(0 if out["fits"] and out["analytic_agrees_with_compiler"]
             else 1)


if __name__ == "__main__":
    main()
