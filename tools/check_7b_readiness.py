"""7B readiness proof (VERDICT r2 next #8).

``TransformerConfig.llama2_7b()`` is exercised for real: the FULL fsdp-sharded
train step (forward, backward, AdamW update) is lowered AND compiled — no
execution, no 7B buffers allocated — against an 8-virtual-device CPU mesh,
exactly the program a v5e/v5p slice would run. Alongside, an HBM budget table
(params / optimizer / gradients / activation estimate per chip) is printed for
fsdp=8/16/32 against v5e (16 GiB) and v5p (95 GiB) chips, so the v5p-32 north
star (BASELINE.md) is a launch away, not a hope.

Usage:  python tools/check_7b_readiness.py [--devices 8] [--batch-per-shard 1]
                                           [--seq-len 2048] [--skip-compile]
Prints one JSON line at the end; exit 0 = compile succeeded + fits v5p-32.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

GiB = 1024**3
CHIP_HBM = {"v5e": 16 * GiB, "v5p": 95 * GiB}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--batch-per-shard", type=int, default=1)
    ap.add_argument("--seq-len", type=int, default=2048)
    ap.add_argument("--skip-compile", action="store_true")
    ap.add_argument("--out", default=os.path.join(REPO,
                                                  "SEVENB_READINESS.json"))
    a = ap.parse_args()

    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={a.devices}"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from fedml_tpu.parallel.pipeline import _opt_state_specs
    from fedml_tpu.parallel.sharding import make_mesh
    from fedml_tpu.parallel.train_step import CheetahTrainer, make_optimizer
    from fedml_tpu.parallel.transformer import TransformerConfig

    import dataclasses

    cfg = dataclasses.replace(
        TransformerConfig.llama2_7b(), max_seq_len=a.seq_len
    )
    mesh = make_mesh({"fsdp": a.devices})
    trainer = CheetahTrainer(cfg, mesh, optimizer=make_optimizer(3e-4))

    # ---- abstract state: shapes via eval_shape, shardings from the trainer
    t0 = time.time()
    params_abs = jax.eval_shape(
        trainer._init_raw, jax.random.PRNGKey(0)
    )["params"]
    opt_abs = jax.eval_shape(trainer.opt.init, params_abs)
    p_spec = jax.tree.map(lambda s: s.spec, trainer.param_shardings,
                          is_leaf=lambda x: isinstance(x, NamedSharding))
    o_spec = _opt_state_specs(p_spec, opt_abs)

    def sds(abs_leaf, spec):
        return jax.ShapeDtypeStruct(
            abs_leaf.shape, abs_leaf.dtype,
            sharding=NamedSharding(mesh, spec),
        )

    from fedml_tpu.parallel.train_step import TrainState

    state_abs = TrainState(
        step=sds(jax.ShapeDtypeStruct((), jnp.int32), P()),
        params=jax.tree.map(sds, params_abs, p_spec),
        opt_state=jax.tree.map(
            sds, opt_abs, o_spec,
            is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct),
        ),
    )
    B = a.batch_per_shard * a.devices
    tok_sds = jax.ShapeDtypeStruct(
        (B, a.seq_len), jnp.int32, sharding=trainer._batch_shard
    )

    # ---- exact parameter/optimizer byte counts (fp32 master + AdamW moments)
    def tree_bytes(tree):
        return sum(
            int(x.size) * jnp.dtype(x.dtype).itemsize
            for x in jax.tree.leaves(tree)
        )

    n_params = sum(int(x.size) for x in jax.tree.leaves(params_abs))
    params_bytes = tree_bytes(params_abs)
    opt_bytes = tree_bytes(opt_abs)
    grads_bytes = params_bytes  # transient fp32 gradient tree

    # ---- compile the sharded step (no execution, no buffers) --------------
    compile_ok = None
    compile_s = None
    temp_bytes_per_chip = None
    if not a.skip_compile:
        with mesh:
            lowered = trainer._step_jit.lower(state_abs, tok_sds, tok_sds)
            t1 = time.time()
            compiled = lowered.compile()
            compile_s = round(time.time() - t1, 1)
        compile_ok = True
        try:
            ma = compiled.memory_analysis()
            # per-device temps (activations + workspace) as compiled
            temp_bytes_per_chip = int(ma.temp_size_in_bytes)
        except Exception:
            temp_bytes_per_chip = None
        print(f"7B train step compiled in {compile_s}s "
              f"(lower {round(t1 - t0, 1)}s) on mesh fsdp={a.devices}")

    # ---- analytic activation estimate for the remat policy ----------------
    # remat=True ("full"): per layer the block INPUT is saved — [B, L, D]
    # bf16 — plus attention workspace for ONE layer's recompute at a time.
    D, L_, nl = cfg.d_model, a.seq_len, cfg.n_layers
    act_saved = B * L_ * D * 2 * nl  # saved block inputs, whole batch
    act_work = B * L_ * (D * 6) * 2  # one block's recompute live set (approx)
    logits_chunk = B * trainer.loss_chunk * cfg.vocab_size * 4 if trainer.loss_chunk else B * L_ * cfg.vocab_size * 4
    act_est_total = act_saved + act_work + logits_chunk

    rows = []
    for n_chips in (8, 16, 32):
        per = {
            "params": params_bytes / n_chips,
            "optimizer": opt_bytes / n_chips,
            "grads": grads_bytes / n_chips,
            # activations scale with the PER-CHIP batch (fixed here)
            "activations_est": act_est_total / a.devices,
        }
        total = sum(per.values())
        rows.append({
            "fsdp": n_chips,
            **{k: round(v / GiB, 2) for k, v in per.items()},
            "total_gib_per_chip": round(total / GiB, 2),
            "fits_v5e": total < CHIP_HBM["v5e"] * 0.9,
            "fits_v5p": total < CHIP_HBM["v5p"] * 0.9,
        })

    print(f"\n7B HBM budget (batch/shard={a.batch_per_shard}, "
          f"seq={a.seq_len}, remat={cfg.remat}, "
          f"params={n_params/1e9:.2f}B):")
    hdr = ("fsdp", "params", "optimizer", "grads", "activations_est",
           "total_gib_per_chip", "fits_v5e", "fits_v5p")
    print("  " + "  ".join(f"{h:>18}" for h in hdr))
    for r in rows:
        print("  " + "  ".join(f"{str(r[h]):>18}" for h in hdr))
    if temp_bytes_per_chip is not None:
        print(f"  (XLA temp buffer per chip at fsdp={a.devices}: "
              f"{temp_bytes_per_chip / GiB:.2f} GiB — CPU-backend layout "
              f"with different fusion/remat decisions than TPU; NOT an HBM "
              f"prediction, use the analytic rows)")

    out = {
        "params_b": round(n_params / 1e9, 3),
        "compile_ok": compile_ok,
        "compile_s": compile_s,
        "mesh": {"fsdp": a.devices},
        "budget": rows,
        "xla_temp_gib_per_chip": (
            round(temp_bytes_per_chip / GiB, 2)
            if temp_bytes_per_chip is not None else None
        ),
    }
    print(json.dumps(out))
    if not a.skip_compile:
        with open(a.out, "w") as f:
            json.dump(out, f, indent=2)
    ok = (compile_ok is not False) and rows[-1]["fits_v5p"]
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
