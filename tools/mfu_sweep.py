"""Sweep Cheetah single-chip configs for MFU — each config in a FRESH process.

HBM on the axon chip is not reclaimed promptly across trainer rebuilds inside
one process (dead state poisons later measurements), so the parent spawns one
subprocess per config and reads a JSON line back.

Usage:
  python tools/mfu_sweep.py            # run the sweep matrix
  python tools/mfu_sweep.py --one '{"n_heads": 8, ...}'   # child mode
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the shipped bench flagship (bench.py bench_cheetah): d2048 x 8L, GQA
# 16q/4kv — the Llama-standard head_dim 128. Native-GQA splash
# (make_splash_mqa, no K/V repeat) + explicit (512, 512) kernel blocks
# measured 75.7% MFU on the v5e, vs 42% for the same shape through the
# old expand-to-MHA path and 68% for the r2 wide-head (hd512) flagship.
BASE = dict(
    vocab_size=32000, d_model=2048, n_layers=8, n_heads=16, n_kv_heads=4,
    d_ff=5632, max_seq_len=2048, remat=False, remat_policy="full",
    attn_impl="auto", batch=8, seq=2048, steps=15, loss_chunk=256,
    mu_bf16=True, attn_block_q=512, attn_block_kv=512,
)


def run_one(cfg: dict) -> None:
    sys.path.insert(0, REPO)
    from bench import TPU_PEAK_FLOPS, _maybe_force_platform

    _maybe_force_platform()  # BENCH_PLATFORM=cpu — off-TPU driving
    import jax
    import jax.numpy as jnp
    import numpy as np

    from fedml_tpu.parallel.sharding import make_mesh
    from fedml_tpu.parallel.train_step import CheetahTrainer, make_optimizer
    from fedml_tpu.parallel.transformer import TransformerConfig

    B, L, steps = cfg.pop("batch"), cfg.pop("seq"), cfg.pop("steps")
    loss_chunk = cfg.pop("loss_chunk")
    mu_bf16 = cfg.pop("mu_bf16", False)
    if jax.devices()[0].platform != "tpu":
        # the matrix shapes are TPU-sized; grinding them on CPU just burns
        # the caller's timeout (bench.py's hd512 secondary relies on this)
        print(json.dumps({"skipped": "not a tpu host"}))
        return
    tc = TransformerConfig(**cfg)
    mesh = make_mesh()
    tr = CheetahTrainer(
        tc, mesh,
        optimizer=make_optimizer(
            3e-4, warmup_steps=10, total_steps=100,
            mu_dtype=jnp.bfloat16 if mu_bf16 else None,
        ),
        loss_chunk=loss_chunk,
    )
    state = tr.init_state(jax.random.PRNGKey(0))
    n_params = sum(int(p.size) for p in jax.tree.leaves(state.params))
    # MoE: FLOPs follow ACTIVE params — each token visits top_k of E
    # experts, so expert FFN weights count at top_k/E (standard MoE MFU
    # convention); router/attention/embed count fully
    n_active = n_params
    if tc.moe_experts > 1:
        import jax.tree_util as jtu

        expert_params = sum(
            int(leaf.size)
            for path, leaf in jtu.tree_flatten_with_path(state.params)[0]
            if any("MoEFeedForward" in str(getattr(k, "key", k)) for k in path)
            and any(str(getattr(k, "key", k)) in ("w_gate_up", "w_down")
                    for k in path)
        )
        top_k = int(getattr(tc, "moe_top_k", 1))
        n_active = n_params - expert_params \
            + expert_params * top_k // tc.moe_experts
    rng = np.random.RandomState(0)
    tok = jnp.asarray(rng.randint(0, tc.vocab_size, (B, L)).astype(np.int32))
    mask = jnp.ones((B, L), jnp.int32)
    # go through train_step (not _step_jit): it scopes the mesh_context the
    # Pallas kernels need to shard_map themselves on multi-chip meshes
    # >= 2 warmup steps: the FIRST step compiles, and the SECOND
    # recompiles (the donated state comes back with step-output
    # shardings that differ from init_state's) — timing from warmup=1
    # puts that second ~10 s compile inside the measured window and
    # under-reports MFU by 2-3x
    for _ in range(3):
        state, m = tr.train_step(state, tok, mask)
    float(np.asarray(m["loss"]))  # true sync (axon block_until_ready no-op)
    t0 = time.perf_counter()
    for _ in range(steps):
        state, m = tr.train_step(state, tok, mask)
    float(np.asarray(m["loss"]))
    dt = (time.perf_counter() - t0) / steps
    fpt = 6.0 * n_active + 12.0 * L * tc.n_layers * tc.d_model
    n_chips = jax.device_count()
    tps = B * L / dt / n_chips  # per chip (mesh spans all local devices)
    peak = TPU_PEAK_FLOPS.get(jax.devices()[0].device_kind, 197e12)
    line = {
        "step_s": round(dt, 3), "tok_s": round(tps),
        "params_m": round(n_params / 1e6, 1),
        "n_chips": n_chips,
        "mfu": round(tps * fpt / peak, 4),
        "device_kind": jax.devices()[0].device_kind,
    }
    if n_active != n_params:
        line["params_active_m"] = round(n_active / 1e6, 1)
    print(json.dumps(line))


def main() -> None:
    if len(sys.argv) > 2 and sys.argv[1] == "--one":
        run_one(json.loads(sys.argv[2]))
        return
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--matrix", default="")
    ns = ap.parse_args()
    if ns.matrix:
        matrix = json.loads(ns.matrix)
    else:
        matrix = [
            dict(),  # the shipped flagship (75.7% MFU measured on v5e)
            # block-size curve for hd128 (the flagship's main lever):
            # kernel-default blocks → 47%, (512,1024) → 75.5%,
            # (512,512) → 75.7%
            dict(attn_block_q=0, attn_block_kv=0),
            dict(attn_block_q=512, attn_block_kv=1024),
            # GQA ratio at hd128: 16/16 (MHA) → 42% via old path;
            # 16/8 → 74%; 16/4 (flagship) → 75.7%
            dict(n_kv_heads=8),
            # the r2 wide-head flagship (4q/2kv hd512): 68%
            dict(n_heads=4, n_kv_heads=2, attn_block_q=0, attn_block_kv=0),
            # memory ladder fallbacks
            dict(remat=True, remat_policy="dots"),
            dict(remat=True, remat_policy="full"),
        ]
    for delta in matrix:
        cfg = {**BASE, **delta}
        tag = json.dumps(delta) if delta else "base"
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        p = subprocess.run(
            [sys.executable, __file__, "--one", json.dumps(cfg)],
            capture_output=True, text=True, timeout=900, env=env,
        )
        line = (p.stdout.strip().splitlines() or ["<no output>"])[-1]
        err = (p.stderr.strip().splitlines() or [""])[-1] if p.returncode else ""
        print(f"{tag:55s} {line} {err[:120]}", flush=True)


if __name__ == "__main__":
    main()
