#!/usr/bin/env bash
# CI smoke for the crash-recovery plane (fedml_tpu/chaos.py): a loopback
# cross-silo federation under a seeded fault matrix — 10% visible message
# loss + 20% wire duplication + 20% payload corruption + one mid-run
# self-SIGTERM — restarted with --resume auto, must produce final global
# params BITWISE EQUAL to a fault-free reference run, with no client
# contribution counted twice (per-round contribution counters from the
# durable run ledger).
#
# This is the executable form of the robustness contract in
# docs/robustness.md; tests/test_chaos.py is the fine-grained half.
#
# Usage: tools/chaos_smoke.sh          (CI: exits non-zero on any regression)
set -uo pipefail
cd "$(dirname "$0")/.."

workdir=$(mktemp -d /tmp/fedml_chaos_smoke.XXXXXX)
trap 'rm -rf "$workdir"' EXIT

out=$(timeout -k 10 300 env JAX_PLATFORMS=cpu python -m fedml_tpu.cli chaos \
    --clients 2 --rounds 4 --seed 7 \
    --loss 0.1 --duplicate 0.2 --corrupt 0.2 \
    --kill-round 1 --workdir "$workdir" 2>/dev/null)
rc=$?

if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
    echo "chaos_smoke: FAIL — harness hit the hard timeout (rc=$rc)" >&2
    exit 1
fi
if [ "$rc" -ne 0 ]; then
    echo "chaos_smoke: FAIL — chaos harness exited rc=$rc" >&2
    printf '%s\n' "$out" >&2
    exit 1
fi

python - "$out" <<'EOF'
import json
import sys

verdict = json.loads(sys.argv[1])
assert verdict["ok"], verdict["problems"]
assert verdict["parity"], verdict["problems"]
print("chaos_smoke: OK —",
      f"{verdict['rounds']} rounds x {verdict['clients']} clients,",
      f"faults={verdict['fault_matrix']},",
      f"preemption_exercised={verdict['preemption_exercised']}")
EOF
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "chaos_smoke: FAIL — verdict did not validate" >&2
    exit 1
fi

# ---- compressed/delta leg (ISSUE 9 satellite) ------------------------------
# the SAME fault matrix + kill/resume with the delta delivery plane on:
# compressed C2S deltas (stateless quantize) + lossless S2C delta frames —
# dedup and payload digests must survive DELTA frames bitwise. Fresh
# workdir: the delivery config is run-ledger identity, so reusing leg 1's
# checkpoints would be (correctly) refused.
workdir_c=$(mktemp -d /tmp/fedml_chaos_smoke_comp.XXXXXX)
trap 'rm -rf "$workdir" "$workdir_c"' EXIT
out=$(timeout -k 10 300 env JAX_PLATFORMS=cpu python -m fedml_tpu.cli chaos \
    --clients 2 --rounds 4 --seed 7 \
    --loss 0.1 --duplicate 0.2 --corrupt 0.2 \
    --compression quantize \
    --kill-round 1 --workdir "$workdir_c" 2>/dev/null)
rc=$?

if [ "$rc" -ne 0 ]; then
    echo "chaos_smoke: FAIL — compressed chaos leg exited rc=$rc" >&2
    printf '%s\n' "$out" >&2
    exit 1
fi

python - "$out" <<'EOF'
import json
import sys

verdict = json.loads(sys.argv[1])
assert verdict["ok"], verdict["problems"]
assert verdict["parity"], verdict["problems"]
print("chaos_smoke: compressed/delta OK —",
      f"{verdict['rounds']} rounds x {verdict['clients']} clients,",
      f"preemption_exercised={verdict['preemption_exercised']}")
EOF
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "chaos_smoke: FAIL — compressed verdict did not validate" >&2
    exit 1
fi

# ---- multiprocess gRPC leg (ISSUE 7 satellite) -----------------------------
# the SAME fault matrix + kill/resume, but the clients are real OS processes
# over gRPC (spawned via the swarm harness's ProcSpawner); parity is checked
# against the fault-free LOOPBACK reference, so bitwise equality must hold
# ACROSS transports
workdir2=$(mktemp -d /tmp/fedml_chaos_smoke_grpc.XXXXXX)
trap 'rm -rf "$workdir" "$workdir_c" "$workdir2"' EXIT

# rounds 6 x epochs 2 keeps the federation alive long enough past the
# round-1 ledger commit for the self-SIGTERM to land (a faster world can
# outrun the watcher; the verdict stays valid either way and reports
# preemption_exercised)
out=$(timeout -k 10 420 env JAX_PLATFORMS=cpu python -m fedml_tpu.cli chaos \
    --clients 2 --rounds 6 --epochs 2 --seed 7 \
    --loss 0.05 --duplicate 0.1 --corrupt 0.1 \
    --kill-round 1 --transport grpc --timeout 300 \
    --workdir "$workdir2" 2>/dev/null)
rc=$?

if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
    echo "chaos_smoke: FAIL — gRPC leg hit the hard timeout (rc=$rc)" >&2
    exit 1
fi
if [ "$rc" -ne 0 ]; then
    echo "chaos_smoke: FAIL — gRPC chaos leg exited rc=$rc" >&2
    printf '%s\n' "$out" >&2
    exit 1
fi

python - "$out" <<'EOF'
import json
import sys

verdict = json.loads(sys.argv[1])
assert verdict["ok"], verdict["problems"]
assert verdict["parity"], verdict["problems"]
print("chaos_smoke: gRPC multiprocess OK —",
      f"{verdict['rounds']} rounds x {verdict['clients']} client procs,",
      f"preemption_exercised={verdict['preemption_exercised']}")
EOF
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "chaos_smoke: FAIL — gRPC verdict did not validate" >&2
    exit 1
fi

# ---- server-kill leg, loopback (ISSUE 12 tentpole) -------------------------
# SIGKILL (no drain) at a protocol phase of round 1, restart with --resume
# auto: bitwise parity with the fault-free reference AND exactly one ledger
# entry per committed round. tests/test_failover.py covers all three phases;
# the smoke pins one mid-protocol phase per transport.
workdir_k=$(mktemp -d /tmp/fedml_chaos_smoke_kill.XXXXXX)
trap 'rm -rf "$workdir" "$workdir_c" "$workdir2" "$workdir_k"' EXIT
out=$(timeout -k 10 300 env JAX_PLATFORMS=cpu python -m fedml_tpu.cli chaos \
    --clients 2 --rounds 3 --seed 7 \
    --loss 0.05 --duplicate 0.1 --corrupt 0.1 \
    --kill-round 1 --kill-phase mid_fold --workdir "$workdir_k" 2>/dev/null)
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "chaos_smoke: FAIL — server-kill (loopback) leg exited rc=$rc" >&2
    printf '%s\n' "$out" >&2
    exit 1
fi
python - "$out" <<'EOF'
import json
import sys

verdict = json.loads(sys.argv[1])
assert verdict["ok"], verdict["problems"]
assert verdict["parity"], verdict["problems"]
assert verdict["preemption_exercised"], "the SIGKILL never fired"
# the flight recorder's post-mortem must name the exact kill phase+round
# (docs/tracing.md), and the merged trace must be orphan-free across the
# kill+restart
fr = verdict["flight_recorder"]
assert fr and fr["phase"] == "mid_fold", fr
assert fr["round"] == 1, fr
assert verdict["trace_spans"] > 0, verdict
assert verdict["trace_orphans"] == 0, verdict
print("chaos_smoke: server-kill (loopback, mid_fold) OK —",
      f"{verdict['rounds']} rounds x {verdict['clients']} clients,",
      f"post-mortem names {fr['phase']}@r{fr['round']},",
      f"{verdict['trace_spans']} spans 0 orphans")
EOF
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "chaos_smoke: FAIL — server-kill verdict did not validate" >&2
    exit 1
fi

# ---- server-kill leg, gRPC crash-failover ----------------------------------
# the client processes are owned by the ORCHESTRATOR and survive the server
# SIGKILL: they must heartbeat-miss, reconnect (stale channel evicted),
# c2s_resync onto the restarted server-only worker at the same port, replay
# anything uncommitted, and reach FINISH with exit 0
workdir_kg=$(mktemp -d /tmp/fedml_chaos_smoke_killg.XXXXXX)
trap 'rm -rf "$workdir" "$workdir_c" "$workdir2" "$workdir_k" "$workdir_kg"' EXIT
out=$(timeout -k 10 480 env JAX_PLATFORMS=cpu python -m fedml_tpu.cli chaos \
    --clients 2 --rounds 3 --epochs 2 --seed 7 \
    --loss 0.05 --duplicate 0.1 --corrupt 0.1 \
    --kill-round 1 --kill-phase post_commit --transport grpc \
    --timeout 360 --workdir "$workdir_kg" 2>/dev/null)
rc=$?
if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
    echo "chaos_smoke: FAIL — gRPC failover leg hit the hard timeout" >&2
    exit 1
fi
if [ "$rc" -ne 0 ]; then
    echo "chaos_smoke: FAIL — gRPC failover leg exited rc=$rc" >&2
    printf '%s\n' "$out" >&2
    exit 1
fi
python - "$out" <<'EOF'
import json
import sys

verdict = json.loads(sys.argv[1])
assert verdict["ok"], verdict["problems"]
assert verdict["parity"], verdict["problems"]
assert verdict["preemption_exercised"], "the SIGKILL never fired"
fr = verdict["flight_recorder"]
assert fr and fr["phase"] == "post_commit", fr
assert verdict["trace_orphans"] == 0, verdict
print("chaos_smoke: server-kill (gRPC failover, post_commit) OK —",
      "surviving client procs resynced across the restart,",
      f"post-mortem names {fr['phase']}@r{fr['round']}")
EOF
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "chaos_smoke: FAIL — gRPC failover verdict did not validate" >&2
    exit 1
fi

# ---- partition leg ---------------------------------------------------------
# a 1.2 s bidirectional server<->clients cut 1 s into the world: the
# at-least-once retry budget must absorb it with bitwise parity
workdir_p=$(mktemp -d /tmp/fedml_chaos_smoke_part.XXXXXX)
trap 'rm -rf "$workdir" "$workdir_c" "$workdir2" "$workdir_k" "$workdir_kg" "$workdir_p"' EXIT
out=$(timeout -k 10 300 env JAX_PLATFORMS=cpu python -m fedml_tpu.cli chaos \
    --clients 2 --rounds 4 --seed 7 \
    --loss 0.05 --duplicate 0.1 --corrupt 0.1 \
    --kill-round -1 --partition 1.0:1.2 --workdir "$workdir_p" 2>/dev/null)
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "chaos_smoke: FAIL — partition leg exited rc=$rc" >&2
    printf '%s\n' "$out" >&2
    exit 1
fi
python - "$out" <<'EOF'
import json
import sys

verdict = json.loads(sys.argv[1])
assert verdict["ok"], verdict["problems"]
assert verdict["parity"], verdict["problems"]
print("chaos_smoke: partition OK —",
      f"window {verdict['fault_matrix']['partition']} absorbed bitwise")
EOF
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "chaos_smoke: FAIL — partition verdict did not validate" >&2
    exit 1
fi

# ---- edge-kill leg (ISSUE 19 tentpole) -------------------------------------
# the faulty world runs 2-TIER (clients → 2 edge aggregators → root) while
# the reference stays FLAT and fault-free; the first edge is fail-stopped
# the moment a client update reaches it (pre_fold). Its orphaned clients
# must re-home to the sibling edge (or root degraded mode) and replay their
# cached still-stamped updates — and the run must STILL land bitwise on the
# flat reference params with exactly one ledger contribution per
# (client, round). Parity here proves the tier is a transport, not a math
# change, even while a whole failure domain dies.
workdir_e=$(mktemp -d /tmp/fedml_chaos_smoke_edge.XXXXXX)
trap 'rm -rf "$workdir" "$workdir_c" "$workdir2" "$workdir_k" "$workdir_kg" "$workdir_p" "$workdir_e"' EXIT
out=$(timeout -k 10 300 env JAX_PLATFORMS=cpu python -m fedml_tpu.cli chaos \
    --clients 4 --rounds 2 --seed 7 \
    --loss 0.05 --duplicate 0.1 --corrupt 0.1 \
    --kill-round -1 --edges 2 --kill-edge pre_fold \
    --workdir "$workdir_e" 2>/dev/null)
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "chaos_smoke: FAIL — edge-kill leg exited rc=$rc" >&2
    printf '%s\n' "$out" >&2
    exit 1
fi
python - "$out" <<'EOF'
import json
import sys

verdict = json.loads(sys.argv[1])
assert verdict["ok"], verdict["problems"]
assert verdict["parity"], verdict["problems"]
et = verdict["edge_tier"]
assert et, verdict
assert et["edge_kill_exercised"], "armed pre_fold edge kill never fired"
assert et["killed_edges"], et
# the corpse's clients found a new home (sibling edge and/or root)
assert et["rehomed_clients"] + et["root_adoptions"] > 0, et
# cached-replay dedup accounting is visible, not silent
assert et["direct_client_updates"] == 0 or et["root_adoptions"] > 0, et
print("chaos_smoke: edge-kill (pre_fold) OK —",
      f"killed edge(s) {et['killed_edges']},",
      f"{et['rehomed_clients']:.0f} re-homed /",
      f"{et['root_adoptions']:.0f} root-adopted, bitwise parity holds")
EOF
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "chaos_smoke: FAIL — edge-kill verdict did not validate" >&2
    exit 1
fi

# ---- root–edge partition leg (ISSUE 19 tentpole) ---------------------------
# cut the first edge off from the root for 2 s starting 1 s in: the edge
# rides the cut on its resync FSM (heartbeat misses → suspect → resync →
# replay its cached summary) and the root's committed-round guard + dedup
# window absorb whatever had already crossed before the cut — bitwise
# parity with the flat fault-free reference under at-least-once delivery
workdir_ep=$(mktemp -d /tmp/fedml_chaos_smoke_epart.XXXXXX)
trap 'rm -rf "$workdir" "$workdir_c" "$workdir2" "$workdir_k" "$workdir_kg" "$workdir_p" "$workdir_e" "$workdir_ep"' EXIT
out=$(timeout -k 10 300 env JAX_PLATFORMS=cpu python -m fedml_tpu.cli chaos \
    --clients 4 --rounds 3 --seed 7 \
    --loss 0.05 --duplicate 0.1 --corrupt 0.1 \
    --kill-round -1 --edges 2 --edge-partition 1.0:2.0 \
    --workdir "$workdir_ep" 2>/dev/null)
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "chaos_smoke: FAIL — root-edge partition leg exited rc=$rc" >&2
    printf '%s\n' "$out" >&2
    exit 1
fi
python - "$out" <<'EOF'
import json
import sys

verdict = json.loads(sys.argv[1])
assert verdict["ok"], verdict["problems"]
assert verdict["parity"], verdict["problems"]
et = verdict["edge_tier"]
assert et, verdict
# no edge died — this leg is pure partition
assert not et["killed_edges"], et
# the cut actually bit: the edge missed heartbeats and/or replayed its
# cached summary through the resync FSM
assert et["heartbeat_misses"] + et["resync_replays"] > 0, et
print("chaos_smoke: root-edge partition OK —",
      f"window {verdict['fault_matrix']['edge_partition']} absorbed,",
      f"{et['heartbeat_misses']:.0f} heartbeat misses /",
      f"{et['resync_replays']:.0f} summary replays, bitwise parity holds")
EOF
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "chaos_smoke: FAIL — root-edge partition verdict did not validate" >&2
    exit 1
fi
echo "chaos_smoke: PASS"
