#!/usr/bin/env bash
# CI smoke for ALL SIX static-analysis gates:
#  - graftlint  (G001–G005, JAX trace/donation/recompile/thread safety)
#  - graftproto (P001–P009, comm-plane protocol + lock-order verification)
#  - graftshard (S001–S005, sharding/HBM verification of the TPU
#                execution plane)
#  - graftrep   (D001–D006, determinism discipline + fused/unfused round
#                equivalence of the trust pipeline)
#  - graftiso   (I001–I005, serving-plane state ownership, tenant
#                isolation & thread lifecycle)
#  - graftmem   (M001–M005, serving-plane retention: bounded containers,
#                capped caches, fixed metric vocabularies, drained
#                parking, released payloads)
# The shipped tree must have ZERO non-baselined findings in each suite
# (tools/<suite>/baseline.json holds the suppressed-but-visible debt —
# graftshard's, graftrep's, graftiso's and graftmem's ship EMPTY), the
# JSON reports must parse, and each gate must bite on a known-bad fixture.
#
# Exit-code contract (all suites): 0 clean, 1 findings, 2 analyzer crash —
# a CI failure here is diagnosable at a glance.
#
# This is the cheap half of the tier-1 lint gate (tests/test_graftlint.py
# + test_graftproto.py + test_graftshard.py + test_graftrep.py +
# test_graftiso.py + test_graftmem.py are the full ones): pure-AST, no
# jax import, sub-second.
#
# Usage: tools/lint_smoke.sh          (CI: exits non-zero on any regression)
set -uo pipefail
cd "$(dirname "$0")/.."

out=$(timeout -k 10 120 python -m tools.graftlint fedml_tpu/ --format json)
rc=$?

if [ "$rc" -ne 0 ]; then
    echo "lint_smoke: FAIL — graftlint exited rc=$rc" >&2
    printf '%s\n' "$out" >&2
    exit 1
fi

python - "$out" <<'EOF'
import json
import sys

payload = json.loads(sys.argv[1])
assert payload["exit_code"] == 0, payload
assert payload["findings"] == [], payload["findings"]
print(f"lint_smoke: graftlint OK — 0 findings "
      f"({payload['baselined']} baselined)")
EOF
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "lint_smoke: FAIL — graftlint JSON output did not validate" >&2
    exit 1
fi

# the gate must actually bite: a known-bad fixture has to exit non-zero
if python -m tools.graftlint tests/fixtures/graftlint/g001_bad.py \
        --no-baseline >/dev/null 2>&1; then
    echo "lint_smoke: FAIL — graftlint passed a known-bad fixture" >&2
    exit 1
fi

# ---- graftproto: the protocol pass, machine-readable -----------------------
proto_out=$(timeout -k 10 120 python -m tools.graftproto fedml_tpu/ --json)
rc=$?

if [ "$rc" -ne 0 ]; then
    echo "lint_smoke: FAIL — graftproto exited rc=$rc" >&2
    printf '%s\n' "$proto_out" >&2
    exit 1
fi

python - "$proto_out" <<'EOF'
import json
import sys

payload = json.loads(sys.argv[1])
assert payload["exit_code"] == 0, payload
assert payload["findings"] == [], payload["findings"]
# the flow graph must have classified every wire value — future PRs diff
# these counts to see protocol surface grow/shrink
cov = payload["coverage"]
assert cov, "empty flow-graph coverage"
bad = {v: c for v, c in cov.items()
       if c["classification"] != "sent+handled"}
assert bad == {}, f"unclassified wire values: {bad}"
print(f"lint_smoke: graftproto OK — 0 findings "
      f"({payload['baselined']} baselined, "
      f"{len(cov)} wire values sent+handled)")
EOF
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "lint_smoke: FAIL — graftproto JSON output did not validate" >&2
    exit 1
fi

if python -m tools.graftproto tests/fixtures/graftproto/p008_bad.py \
        --no-baseline >/dev/null 2>&1; then
    echo "lint_smoke: FAIL — graftproto passed a known-bad fixture" >&2
    exit 1
fi

# ---- graftshard: the sharding pass, machine-readable -----------------------
shard_out=$(timeout -k 10 120 python -m tools.graftshard fedml_tpu/ --json)
rc=$?

if [ "$rc" -ne 0 ]; then
    echo "lint_smoke: FAIL — graftshard exited rc=$rc" >&2
    printf '%s\n' "$shard_out" >&2
    exit 1
fi

python - "$shard_out" <<'EOF'
import json
import sys

payload = json.loads(sys.argv[1])
assert payload["exit_code"] == 0, payload
assert payload["findings"] == [], payload["findings"]
# graftshard is the one suite whose baseline must stay EMPTY: the
# execution plane ships fully clean, debt is fixed not suppressed
assert payload["baselined"] == 0, payload
print(f"lint_smoke: graftshard OK — 0 findings (baseline empty)")
EOF
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "lint_smoke: FAIL — graftshard JSON output did not validate" >&2
    exit 1
fi

if python -m tools.graftshard tests/fixtures/graftshard/s002_bad.py \
        --no-baseline >/dev/null 2>&1; then
    echo "lint_smoke: FAIL — graftshard passed a known-bad fixture" >&2
    exit 1
fi

# ---- graftrep: the determinism pass, machine-readable ----------------------
rep_out=$(timeout -k 10 120 python -m tools.graftrep fedml_tpu/ --json)
rc=$?

if [ "$rc" -ne 0 ]; then
    echo "lint_smoke: FAIL — graftrep exited rc=$rc" >&2
    printf '%s\n' "$rep_out" >&2
    exit 1
fi

python - "$rep_out" <<'EOF'
import json
import sys

payload = json.loads(sys.argv[1])
assert payload["exit_code"] == 0, payload
assert payload["findings"] == [], payload["findings"]
# graftrep's baseline must stay EMPTY: the determinism discipline holds
# everywhere the bitwise guarantees reach, debt is fixed not suppressed
assert payload["baselined"] == 0, payload
print("lint_smoke: graftrep OK — 0 findings (baseline empty)")
EOF
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "lint_smoke: FAIL — graftrep JSON output did not validate" >&2
    exit 1
fi

if python -m tools.graftrep tests/fixtures/graftrep/d001_bad.py \
        --no-baseline >/dev/null 2>&1; then
    echo "lint_smoke: FAIL — graftrep passed a known-bad fixture" >&2
    exit 1
fi

# ---- graftiso: the isolation pass, machine-readable ------------------------
iso_out=$(timeout -k 10 120 python -m tools.graftiso fedml_tpu/ --json)
rc=$?

if [ "$rc" -ne 0 ]; then
    echo "lint_smoke: FAIL — graftiso exited rc=$rc" >&2
    printf '%s\n' "$iso_out" >&2
    exit 1
fi

python - "$iso_out" <<'EOF'
import json
import sys

payload = json.loads(sys.argv[1])
assert payload["exit_code"] == 0, payload
assert payload["findings"] == [], payload["findings"]
# graftiso's baseline must stay EMPTY: the serving plane's world-scoping
# contract holds everywhere, debt is fixed not suppressed
assert payload["baselined"] == 0, payload
# the serving model must actually have seen the plane — an empty closure
# would mean the gate silently stopped analyzing anything
serving = payload["serving"]
assert serving["classes"], "no serving classes found"
assert serving["closure_size"] > 0, serving
print(f"lint_smoke: graftiso OK — 0 findings (baseline empty, "
      f"{len(serving['classes'])} serving classes, "
      f"closure {serving['closure_size']})")
EOF
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "lint_smoke: FAIL — graftiso JSON output did not validate" >&2
    exit 1
fi

if python -m tools.graftiso tests/fixtures/graftiso/i005_bad.py \
        --no-baseline >/dev/null 2>&1; then
    echo "lint_smoke: FAIL — graftiso passed a known-bad fixture" >&2
    exit 1
fi

# ---- graftmem: the retention pass, machine-readable ------------------------
mem_out=$(timeout -k 10 120 python -m tools.graftmem fedml_tpu/ --json)
rc=$?

if [ "$rc" -ne 0 ]; then
    echo "lint_smoke: FAIL — graftmem exited rc=$rc" >&2
    printf '%s\n' "$mem_out" >&2
    exit 1
fi

python - "$mem_out" <<'EOF'
import json
import sys

payload = json.loads(sys.argv[1])
assert payload["exit_code"] == 0, payload
assert payload["findings"] == [], payload["findings"]
# graftmem's baseline must stay EMPTY: every piece of serving-plane state
# is bounded/drained/released, debt is fixed not suppressed
assert payload["baselined"] == 0, payload
# the retention model must actually have seen the plane — an empty
# container inventory would mean the gate silently analyzed nothing
mem = payload["mem"]
assert mem["classes"], "no analyzed classes found"
assert mem["containers"] > 0, mem
print(f"lint_smoke: graftmem OK — 0 findings (baseline empty, "
      f"{len(mem['classes'])} analyzed classes, "
      f"{mem['containers']} containers)")
EOF
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "lint_smoke: FAIL — graftmem JSON output did not validate" >&2
    exit 1
fi

if python -m tools.graftmem tests/fixtures/graftmem/m001_bad.py \
        --no-baseline >/dev/null 2>&1; then
    echo "lint_smoke: FAIL — graftmem passed a known-bad fixture" >&2
    exit 1
fi

echo "lint_smoke: PASS"
