#!/usr/bin/env bash
# CI smoke for the graftlint static-analysis gate: the shipped tree must have
# ZERO non-baselined findings (tools/graftlint/baseline.json holds the
# suppressed-but-visible pre-existing debt), and the JSON output must parse.
#
# This is the cheap half of the tier-1 lint gate (tests/test_graftlint.py is
# the full one): pure-AST, no jax import, sub-second.
#
# Usage: tools/lint_smoke.sh          (CI: exits non-zero on any regression)
set -uo pipefail
cd "$(dirname "$0")/.."

out=$(timeout -k 10 120 python -m tools.graftlint fedml_tpu/ --format json)
rc=$?

if [ "$rc" -ne 0 ]; then
    echo "lint_smoke: FAIL — graftlint exited rc=$rc" >&2
    printf '%s\n' "$out" >&2
    exit 1
fi

python - "$out" <<'EOF'
import json
import sys

payload = json.loads(sys.argv[1])
assert payload["exit_code"] == 0, payload
assert payload["findings"] == [], payload["findings"]
print(f"lint_smoke: OK — 0 findings ({payload['baselined']} baselined)")
EOF
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "lint_smoke: FAIL — JSON output did not validate" >&2
    exit 1
fi

# the gate must actually bite: a known-bad fixture has to exit non-zero
if python -m tools.graftlint tests/fixtures/graftlint/g001_bad.py \
        --no-baseline >/dev/null 2>&1; then
    echo "lint_smoke: FAIL — analyzer passed a known-bad fixture" >&2
    exit 1
fi

echo "lint_smoke: PASS"
