"""Head-to-head convergence parity: fedml_tpu vs the reference stack.

VERDICT r2 next #1 — "perf is measured, learning outcomes are not". This tool
feeds IDENTICAL synthetic data, partition, per-round cohorts (both stacks
seed client sampling with the round index — ``fedavg_api.py:125-133`` and
``sp_api.py._client_sampling``), learning rate, epochs, and initial weights
into both stacks and compares the resulting global-model trajectories.

Three parity grades, strongest applicable used per experiment:

1. **Exact trajectory parity vs the reference** (MNIST-shape LR, FedAvg and
   FedProx@mu=0): full-batch local steps make batch order irrelevant, so the
   two stacks compute the same math and the per-round global parameter
   vectors must agree to float32 accumulation error (rel L2 < 1e-3).
   The reference's own ``FedAvgAPI`` runs in-process (torch CPU), exactly as
   ``tools/measure_ref_baseline.py`` drives it. NOTE: as shipped, the
   reference's sp loop is NOT textbook FedAvg — ``get_model_params()``
   (``ml/trainer/my_model_trainer_classification.py:10``) returns live
   tensor references and ``load_state_dict`` writes through them, so each
   client's "copy of w_global" is really the previous client's trained
   weights (sequential chain). The head-to-head therefore runs twice: once
   against the reference with that one getter wrapped to snapshot (textbook
   semantics restored → exact parity required), and once proving the
   as-shipped behavior equals a sequential-chain oracle (so the deviation
   is characterised, not hand-waved).
2. **Exact trajectory parity vs an independent numpy oracle** (FedProx mu>0,
   SCAFFOLD): the reference CANNOT be the oracle here — its FedProx
   (``simulation/mpi/fedprox/``) contains NO proximal term (grep ``mu`` —
   it is FedAvg with renamed classes), and it has no SCAFFOLD at all. The
   oracle is a from-scratch numpy implementation of the published update
   rules (FedProx: Li et al. 2020 eq. 2; SCAFFOLD: Karimireddy et al. 2020,
   option II), written against the papers, not against fedml_tpu's code.
3. **Curve parity** (CIFAR-shape ResNet-56 FedAvg): architectures
   intentionally differ (reference: BatchNorm torch; ours: GroupNorm NHWC —
   a documented TPU re-design), so parameter-level equality is impossible;
   instead both stacks train on the identical federation and must converge
   to the same regime (final accuracy within a stated band).

Model note: the reference's shipped LR (``model/linear/lr.py``) applies a
*sigmoid before CrossEntropyLoss* — an idiosyncrasy, not FedAvg semantics.
Both stacks here use the standard linear-logits + CE model (the reference's
``FedAvgAPI`` accepts any ``torch.nn.Module``), so the parity statement is
about the FL algorithm math, not that quirk.

Usage:
    python tools/parity_check.py [--rounds 20] [--out PARITY.json]   # LR legs (CPU)
    python tools/parity_check.py --resnet-only                       # curve leg (TPU)

Writes PARITY.json (the second invocation merges) and prints one JSON line
per experiment.
"""

from __future__ import annotations

import argparse
import copy
import json
import os
import sys
import time
import types
from unittest import mock

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REF = "/root/reference/python"
sys.path.insert(0, REPO)

# ---------------------------------------------------------------------------
# shared federation: deterministic synthetic data both stacks consume
# ---------------------------------------------------------------------------


def make_federation(seed=0, n_clients=20, per_client=32, n_test=512,
                    shape=(28, 28, 1), n_classes=10, lowfreq=False):
    """Class-conditional Gaussians in the given image shape; per-client
    shards ARE the partition (generated per client, fixed seed).

    ``lowfreq``: class means are coarse 4x4 patterns upsampled to the image
    size instead of iid per-pixel noise — iid-pixel signal is invisible to a
    conv net with global average pooling (the pool averages it to ~0), so
    the ResNet curve leg needs spatially-coherent class structure."""
    rng = np.random.RandomState(seed)
    dim = int(np.prod(shape))
    if lowfreq and len(shape) == 3:
        h, w, c = shape
        coarse = rng.randn(n_classes, 4, 4, c).astype(np.float32)
        up = coarse.repeat(h // 4, axis=1).repeat(w // 4, axis=2)
        means = up.reshape(n_classes, dim) * 0.7
    else:
        means = rng.randn(n_classes, dim).astype(np.float32) * 0.7

    def draw(n, r):
        y = r.randint(0, n_classes, size=n)
        x = means[y] + r.randn(n, dim).astype(np.float32)
        return x.reshape((n,) + shape).astype(np.float32), y.astype(np.int32)

    xs, ys = [], []
    for c in range(n_clients):
        x, y = draw(per_client, np.random.RandomState(seed * 1000 + c + 1))
        xs.append(x)
        ys.append(y)
    test_x, test_y = draw(n_test, np.random.RandomState(seed * 1000 + 999))
    return (np.stack(xs), np.stack(ys),
            np.full((n_clients,), per_client, np.int32), test_x, test_y)


def sample_cohort(round_idx, n_total, per_round):
    """The sampling rule BOTH stacks implement (reference fedavg_api.py:131)."""
    if n_total == per_round:
        return np.arange(n_total)
    rs = np.random.RandomState(round_idx)
    return rs.choice(n_total, per_round, replace=False)


def np_eval(W, b, test_x, test_y):
    """Shared numpy evaluator: CE loss + accuracy of (W [D,C], b [C])."""
    x = test_x.reshape(test_x.shape[0], -1)
    logits = x @ W + b
    logits = logits - logits.max(1, keepdims=True)
    logp = logits - np.log(np.exp(logits).sum(1, keepdims=True))
    loss = float(-logp[np.arange(len(test_y)), test_y].mean())
    acc = float((logits.argmax(1) == test_y).mean())
    return loss, acc


# ---------------------------------------------------------------------------
# stack A: fedml_tpu (CPU platform for float comparability with torch CPU)
# ---------------------------------------------------------------------------


def run_ours_lr(fed, rounds, lr, epochs, per_round, optimizer="FedAvg",
                mu=0.0, init=None):
    """Drive the real sp engine; return [rounds, D*C + C] param trajectory."""
    import jax

    import fedml_tpu as fedml
    from fedml_tpu import models as model_mod
    from fedml_tpu.arguments import Arguments
    from fedml_tpu.data.fed_dataset import FedDataset
    from fedml_tpu.simulation.sp_api import FedAvgAPI

    train_x, train_y, counts, test_x, test_y = fed
    overrides = dict(
        dataset="mnist", model="lr",
        client_num_in_total=int(train_x.shape[0]),
        client_num_per_round=per_round, comm_round=rounds,
        epochs=epochs, batch_size=int(train_x.shape[1]),  # full-batch steps
        learning_rate=lr, client_optimizer="sgd",
        federated_optimizer=optimizer,
    )
    if optimizer == "FedProx":
        # always explicit: the Arguments schema defaults fedprox_mu to 0.1
        overrides["fedprox_mu"] = mu
    args = fedml.init(Arguments(overrides=overrides), should_init_logs=False)
    ds = FedDataset(train_x, train_y, counts, test_x, test_y, class_num=10)
    bundle = model_mod.create(args, 10)
    api = FedAvgAPI(args, fedml.get_device(args), ds, bundle)
    if init is not None:
        W0, b0 = init
        api.global_params = _set_lr_params(api.global_params, W0, b0)

    traj = []
    for r in range(rounds):
        api._train_round(r)
        W, b = _get_lr_params(api.global_params)
        traj.append(np.concatenate([W.ravel(), b.ravel()]))
    return np.stack(traj)


def _lr_leaf_paths(params):
    import jax

    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    kernel = [(p, v) for p, v in flat if v.ndim == 2]
    bias = [(p, v) for p, v in flat if v.ndim == 1]
    assert len(kernel) == 1 and len(bias) == 1, "not an LR param tree"
    return kernel[0][0], bias[0][0]


def _get_lr_params(params):
    import jax

    kpath, bpath = _lr_leaf_paths(params)
    flat = dict(jax.tree_util.tree_flatten_with_path(params)[0])
    return np.asarray(flat[kpath], np.float32), np.asarray(flat[bpath], np.float32)


def _set_lr_params(params, W, b):
    import jax

    kpath, bpath = _lr_leaf_paths(params)

    def setter(path, leaf):
        if path == kpath:
            return np.asarray(W, np.float32)
        if path == bpath:
            return np.asarray(b, np.float32)
        return leaf

    import jax.numpy as jnp

    return jax.tree_util.tree_map_with_path(
        lambda p, x: jnp.asarray(setter(p, x)), params
    )


# ---------------------------------------------------------------------------
# stack B: the reference (torch CPU), driven exactly like measure_ref_baseline
# ---------------------------------------------------------------------------


def _import_with_stubs(name, max_stubs=60):
    stubbed = []
    for _ in range(max_stubs):
        try:
            return __import__(name, fromlist=["_"]), stubbed
        except ModuleNotFoundError as e:
            missing = e.name
            if missing is None or missing in sys.modules:
                raise
            stub = mock.MagicMock(name=f"stub:{missing}")
            stub.__spec__ = types.SimpleNamespace(name=missing)
            stub.__path__ = []
            sys.modules[missing] = stub
            stubbed.append(missing)
    raise RuntimeError(f"too many stubs: {stubbed}")


def _ref_setup():
    if REF not in sys.path:
        sys.path.insert(0, REF)
    import logging

    logging.disable(logging.INFO)
    _import_with_stubs("fedml")


def _torch_linear_init(seed, in_dim=784, out_dim=10):
    """torch's default Linear init under a fixed seed — the shared W0, b0."""
    import torch

    torch.manual_seed(seed)
    lin = torch.nn.Linear(in_dim, out_dim)
    return (lin.weight.detach().numpy().T.copy(),  # ours stores [in, out]
            lin.bias.detach().numpy().copy())


def run_reference_lr(fed, rounds, lr, epochs, per_round, init, model=None,
                     fix_aliasing=False):
    """The reference's own FedAvgAPI on the shared federation; returns the
    per-round [D*C + C] trajectory (torch Linear stores weight [out, in]).

    ``fix_aliasing``: the reference's sp loop has a state-aliasing defect —
    ``w_global = self.model_trainer.get_model_params()`` (fedavg_api.py:67)
    returns LIVE references into the shared trainer's model, and
    ``set_model_params``'s ``load_state_dict`` writes THROUGH those
    references, so ``copy.deepcopy(w_global)`` for client k actually copies
    client k-1's trained weights: as shipped, "FedAvg" is sequential chained
    local training with a mean over the chain's snapshots (verified: a
    sequential-chain oracle matches it to 1e-7, the textbook oracle differs
    by ~0.25 rel L2). With ``fix_aliasing=True`` the getter is wrapped to
    snapshot, which restores textbook FedAvg without touching anything else.
    """
    _ref_setup()
    import torch
    from fedml.simulation.sp.fedavg.fedavg_api import FedAvgAPI

    train_x, train_y, counts, test_x, test_y = fed
    n_clients, per_client = train_x.shape[0], train_x.shape[1]

    def loader(x, y):
        return torch.utils.data.DataLoader(
            torch.utils.data.TensorDataset(
                torch.from_numpy(x.reshape(len(x), -1)),
                torch.from_numpy(y.astype(np.int64)),
            ),
            batch_size=per_client, shuffle=False,
        )

    train_local = {i: loader(train_x[i], train_y[i]) for i in range(n_clients)}
    test_local = {i: loader(test_x[:8], test_y[:8]) for i in range(n_clients)}
    train_num = {i: int(counts[i]) for i in range(n_clients)}
    dataset = [
        int(counts.sum()), len(test_x), None, None,
        train_num, train_local, test_local, 10,
    ]
    ref_args = argparse.Namespace(
        dataset="parity", model="lr", client_num_in_total=n_clients,
        client_num_per_round=per_round, comm_round=rounds, epochs=epochs,
        batch_size=per_client, learning_rate=lr, client_optimizer="sgd",
        weight_decay=0.0, frequency_of_the_test=1, enable_wandb=False,
    )

    if model is None:
        class LinearLogits(torch.nn.Module):
            def __init__(self):
                super().__init__()
                self.linear = torch.nn.Linear(784, 10)

            def forward(self, x):
                return self.linear(x)

        model = LinearLogits()
        W0, b0 = init
        with torch.no_grad():
            model.linear.weight.copy_(torch.from_numpy(W0.T))
            model.linear.bias.copy_(torch.from_numpy(b0))

    api = FedAvgAPI(ref_args, torch.device("cpu"), dataset, model)
    if fix_aliasing:
        orig_get = api.model_trainer.get_model_params
        api.model_trainer.get_model_params = lambda: copy.deepcopy(orig_get())
    traj = []

    def record(round_idx):
        sd = api.model_trainer.get_model_params()
        W = sd["linear.weight"].numpy().T
        b = sd["linear.bias"].numpy()
        traj.append(np.concatenate([W.ravel(), b.ravel()]))

    api._local_test_on_all_clients = record  # capture w_global each round
    api.train()
    return np.stack(traj[:rounds])


# ---------------------------------------------------------------------------
# numpy oracles (published update rules, independent of both stacks)
# ---------------------------------------------------------------------------


def _softmax_grads(W, b, x, y):
    """CE-mean gradients for logits = x@W + b."""
    B = len(y)
    logits = x @ W + b
    logits = logits - logits.max(1, keepdims=True)
    p = np.exp(logits)
    p /= p.sum(1, keepdims=True)
    p[np.arange(B), y] -= 1.0
    p /= B
    return x.T @ p, p.sum(0)


def oracle_as_shipped(fed, rounds, lr, epochs, per_round, init):
    """Oracle of the reference's AS-SHIPPED sp behavior (the aliasing defect
    documented in :func:`run_reference_lr`), pinned empirically:

    - ROUND 0: ``w_global`` aliases the live model, so client k trains from
      client k-1's result (sequential chain); global = mean of snapshots.
    - ROUNDS >= 1: ``w_global`` is rebound to the detached ``_aggregate``
      dict (fedavg_api.py:105), so the aliasing is gone and the update is
      textbook FedAvg — the round-0 contamination just persists in the
      trajectory forever.
    """
    train_x, train_y, counts, _, _ = fed
    K = train_x.shape[0]
    W, b = np.array(init[0], np.float32), np.array(init[1], np.float32)
    traj = []
    for r in range(rounds):
        cohort = sample_cohort(r, K, per_round)
        snaps = []
        curW, curb = W, b
        for ci in cohort:
            x = train_x[ci].reshape(counts[ci], -1)
            y = train_y[ci]
            Wi, bi = (curW.copy(), curb.copy()) if r == 0 else (W.copy(), b.copy())
            for _ in range(epochs):
                gW, gb = _softmax_grads(Wi, bi, x, y)
                Wi -= lr * gW
                bi -= lr * gb
            snaps.append((Wi, bi))
            curW, curb = Wi, bi  # round 0 only: next client starts here
        W = np.mean([s[0] for s in snaps], 0).astype(np.float32)
        b = np.mean([s[1] for s in snaps], 0).astype(np.float32)
        traj.append(np.concatenate([W.ravel(), b.ravel()]))
    return np.stack(traj)


def oracle_lr(fed, rounds, lr, epochs, per_round, init, mu=0.0,
              scaffold=False):
    """FedProx (Li et al. eq.2: +mu/2 ||w - w_t||^2) / SCAFFOLD (Karimireddy
    et al., option II) / FedAvg, full-batch local steps, in plain numpy."""
    train_x, train_y, counts, test_x, test_y = fed
    K = train_x.shape[0]
    W, b = np.array(init[0], np.float32), np.array(init[1], np.float32)
    cW = np.zeros_like(W)
    cb = np.zeros_like(b)
    cWs = np.zeros((K,) + W.shape, np.float32)
    cbs = np.zeros((K,) + b.shape, np.float32)
    traj = []
    for r in range(rounds):
        cohort = sample_cohort(r, K, per_round)
        newWs, newbs, weights = [], [], []
        newcW, newcb = [], []
        for ci in cohort:
            x = train_x[ci].reshape(counts[ci], -1)
            y = train_y[ci]
            Wi, bi = W.copy(), b.copy()
            steps = 0
            for _ in range(epochs):
                gW, gb = _softmax_grads(Wi, bi, x, y)
                if mu > 0.0:
                    gW = gW + mu * (Wi - W)
                    gb = gb + mu * (bi - b)
                if scaffold:
                    gW = gW + cW - cWs[ci]
                    gb = gb + cb - cbs[ci]
                Wi -= lr * gW
                bi -= lr * gb
                steps += 1
            newWs.append(Wi)
            newbs.append(bi)
            weights.append(float(counts[ci]))
            if scaffold:
                tau = float(steps)
                newcW.append(cWs[ci] - cW + (W - Wi) / (tau * lr))
                newcb.append(cbs[ci] - cb + (b - bi) / (tau * lr))
        w = np.asarray(weights, np.float32)
        w /= w.sum()
        W = sum(wi * Wi for wi, Wi in zip(w, newWs)).astype(np.float32)
        b = sum(wi * bi for wi, bi in zip(w, newbs)).astype(np.float32)
        if scaffold:
            dW = np.mean([nc - cWs[ci] for nc, ci in zip(newcW, cohort)], 0)
            db = np.mean([nc - cbs[ci] for nc, ci in zip(newcb, cohort)], 0)
            scale = len(cohort) / K
            cW = cW + scale * dW
            cb = cb + scale * db
            for nc, nb, ci in zip(newcW, newcb, cohort):
                cWs[ci] = nc
                cbs[ci] = nb
        traj.append(np.concatenate([W.ravel(), b.ravel()]))
    return np.stack(traj)


# ---------------------------------------------------------------------------
# ResNet-56 curve parity (architectures differ by design: BN vs GN)
# ---------------------------------------------------------------------------


def run_resnet_curves(rounds, lr, per_round, n_clients, per_client, seed=0):
    fed = make_federation(seed=seed, n_clients=n_clients,
                          per_client=per_client, n_test=256,
                          shape=(32, 32, 3), n_classes=10, lowfreq=True)
    train_x, train_y, counts, test_x, test_y = fed

    # ours -------------------------------------------------------------
    import fedml_tpu as fedml
    from fedml_tpu import models as model_mod
    from fedml_tpu.arguments import Arguments
    from fedml_tpu.data.fed_dataset import FedDataset
    from fedml_tpu.simulation.sp_api import FedAvgAPI

    args = fedml.init(Arguments(overrides=dict(
        dataset="cifar10", model="resnet56",
        client_num_in_total=n_clients, client_num_per_round=per_round,
        comm_round=rounds, epochs=1, batch_size=32, learning_rate=lr,
        client_optimizer="sgd", frequency_of_the_test=1,
    )), should_init_logs=False)
    ds = FedDataset(train_x, train_y, counts, test_x, test_y, class_num=10)
    bundle = model_mod.create(args, 10)
    api = FedAvgAPI(args, fedml.get_device(args), ds, bundle)
    ours = api.train()

    # reference --------------------------------------------------------
    _ref_setup()
    import torch
    from fedml.model.cv.resnet import resnet56
    from fedml.simulation.sp.fedavg.fedavg_api import FedAvgAPI as RefAPI

    torch.manual_seed(seed)

    def loader(x, y, bs=32):
        return torch.utils.data.DataLoader(
            torch.utils.data.TensorDataset(
                torch.from_numpy(np.transpose(x, (0, 3, 1, 2)).copy()),
                torch.from_numpy(y.astype(np.int64)),
            ), batch_size=bs, shuffle=False,
        )

    train_local = {i: loader(train_x[i], train_y[i]) for i in range(n_clients)}
    test_local = {i: loader(test_x, test_y) for i in range(n_clients)}
    train_num = {i: int(counts[i]) for i in range(n_clients)}
    dataset = [int(counts.sum()), len(test_x), None, None,
               train_num, train_local, test_local, 10]
    ref_args = argparse.Namespace(
        dataset="parity", model="resnet56", client_num_in_total=n_clients,
        client_num_per_round=per_round, comm_round=rounds, epochs=1,
        batch_size=32, learning_rate=lr, client_optimizer="sgd",
        weight_decay=0.0, frequency_of_the_test=10_000, enable_wandb=False,
    )
    ref_api = RefAPI(ref_args, torch.device("cpu"), dataset, resnet56(class_num=10))
    ref_api._local_test_on_all_clients = lambda *_: None
    ref_api.train()

    # shared evaluation of the reference's final global model
    model = ref_api.model_trainer.model
    model.eval()
    with torch.no_grad():
        logits = model(torch.from_numpy(np.transpose(test_x, (0, 3, 1, 2)).copy()))
        ref_acc = float((logits.argmax(1).numpy() == test_y).mean())
    return float(ours["test_acc"]), ref_acc


# ---------------------------------------------------------------------------
# harness
# ---------------------------------------------------------------------------


def rel_err(a, b):
    return float(np.linalg.norm(a - b) / max(np.linalg.norm(b), 1e-12))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=20)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--per-round", type=int, default=5)
    ap.add_argument("--clients", type=int, default=20)
    ap.add_argument("--skip-resnet", action="store_true")
    ap.add_argument("--resnet-only", action="store_true",
                    help="run ONLY the ResNet-56 curve leg and merge into an "
                         "existing PARITY.json. Run this one under the TPU "
                         "env: ResNet-56's XLA:CPU compile takes >35 min on "
                         "this host's single core, while the TPU compiles "
                         "it in seconds — curve parity does not need a "
                         "shared substrate (the LR legs prove exact math "
                         "CPU-vs-CPU).")
    ap.add_argument("--resnet-rounds", type=int, default=50)
    ap.add_argument("--out", default=os.path.join(REPO, "PARITY.json"))
    a = ap.parse_args()

    import jax

    if not a.resnet_only:
        # float-comparable to torch CPU for the exact-trajectory legs
        jax.config.update("jax_platforms", "cpu")
    # persistent compile cache: the ResNet-56 leg's XLA:CPU compile is many
    # minutes on one core; pay it once (same cache the test suite uses)
    cache = os.environ.get("JAX_COMPILATION_CACHE_DIR",
                           "/tmp/fedml_tpu_jax_cache")
    os.makedirs(cache, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    fed = make_federation(n_clients=a.clients)
    init = _torch_linear_init(seed=0)
    _, _, counts, test_x, test_y = fed
    results = {}
    if a.resnet_only and os.path.exists(a.out):
        with open(a.out) as f:
            results = json.load(f).get("results", {})

    def report(name, ours_traj, other_traj, tol, oracle_name):
        per_round = [rel_err(o, r) for o, r in zip(ours_traj, other_traj)]
        W_last = ours_traj[-1][:-10].reshape(784, 10)
        b_last = ours_traj[-1][-10:]
        loss, acc = np_eval(W_last, b_last, test_x, test_y)
        entry = {
            "oracle": oracle_name,
            "rounds": len(per_round),
            "rel_l2_final": per_round[-1],
            "rel_l2_max": max(per_round),
            "tolerance": tol,
            "ok": max(per_round) < tol,
            "final_test_loss": round(loss, 4),
            "final_test_acc": round(acc, 4),
        }
        results[name] = entry
        print(json.dumps({"experiment": name, **entry}))
        return entry

    t0 = time.time()
    common = dict(rounds=a.rounds, lr=a.lr, epochs=a.epochs,
                  per_round=a.per_round)
    if a.resnet_only:
        _run_resnet_leg(a, results)
        _finish(a, results, t0)
        return

    # 1a. FedAvg: ours vs the REFERENCE with its aliasing defect fixed --
    # (one wrapped getter restores textbook FedAvg; see run_reference_lr)
    ours = run_ours_lr(fed, init=init, **common)
    ref_fixed = run_reference_lr(fed, init=init, fix_aliasing=True, **common)
    report("fedavg_lr_vs_reference_aliasing_fixed", ours, ref_fixed, 1e-3,
           "reference FedAvgAPI (torch CPU, in-process; get_model_params "
           "wrapped to snapshot — repairs fedavg_api.py:67's live-reference "
           "aliasing, changing nothing else)")

    # 1b. The as-shipped reference is NOT textbook FedAvg: demonstrate we
    # understand exactly what it does instead (round-0 chain oracle)
    ref_shipped = run_reference_lr(fed, init=init, **common)
    chain = oracle_as_shipped(fed, init=init, **common)
    report("reference_as_shipped_semantics_pinned", chain, ref_shipped,
           1e-3,
           "numpy oracle of the reference's ACTUAL as-shipped semantics: in "
           "round 0, get_model_params() returns live tensor refs, so client "
           "k trains from client k-1's result (sequential chain); from "
           "round 1 w_global is the detached aggregate and updates are "
           "textbook — the as-shipped sp 'FedAvg' is textbook FedAvg from "
           "a chain-contaminated round 0")

    # 2. FedProx@mu=0 degenerates to FedAvg: ours vs the fixed reference
    ours_p0 = run_ours_lr(fed, init=init, optimizer="FedProx", mu=0.0, **common)
    report("fedprox_mu0_lr_vs_reference", ours_p0, ref_fixed, 1e-3,
           "reference FedAvgAPI, aliasing fixed (the reference's FedProx "
           "has no proximal term — simulation/mpi/fedprox carries none; "
           "mu=0 makes the correct algorithm coincide with it)")

    # 3. FedProx@mu>0: ours vs the numpy oracle -------------------------
    mu = 0.5
    ours_p = run_ours_lr(fed, init=init, optimizer="FedProx", mu=mu, **common)
    orac_p = oracle_lr(fed, init=init, mu=mu, **common)
    report("fedprox_mu0.5_lr_vs_oracle", ours_p, orac_p, 1e-3,
           "numpy oracle of Li et al. 2020 eq.2 (reference has no proximal "
           "term to compare against)")

    # 4. SCAFFOLD: ours vs the numpy oracle -----------------------------
    ours_s = run_ours_lr(fed, init=init, optimizer="SCAFFOLD", **common)
    orac_s = oracle_lr(fed, init=init, scaffold=True, **common)
    report("scaffold_lr_vs_oracle", ours_s, orac_s, 1e-3,
           "numpy oracle of Karimireddy et al. 2020 option II (reference "
           "has no SCAFFOLD)")

    # 5. sanity: the trajectories actually LEARN (not parity of no-ops)
    W_last = ours[-1][:-10].reshape(784, 10)
    loss0, acc0 = np_eval(init[0], init[1], test_x, test_y)
    lossN, accN = np_eval(W_last, ours[-1][-10:], test_x, test_y)
    results["learning_sanity"] = {
        "init_acc": round(acc0, 4), "final_acc": round(accN, 4),
        "ok": accN > acc0 + 0.3,
    }
    print(json.dumps({"experiment": "learning_sanity",
                      **results["learning_sanity"]}))

    if not a.skip_resnet:
        print("note: the ResNet-56 curve leg runs as a separate invocation "
              "(--resnet-only, under the TPU env) — see its flag help")
    _finish(a, results, t0)


def _run_resnet_leg(a, results):
    ours_acc, ref_acc = run_resnet_curves(
        rounds=a.resnet_rounds, lr=0.1, per_round=4, n_clients=8,
        per_client=96)
    import jax

    results["resnet56_fedavg_curve"] = {
        "oracle": "reference FedAvgAPI + torch resnet56 (BatchNorm, CPU) — "
                  "curve-level only: ours is the documented GroupNorm NHWC "
                  "redesign, run on "
                  f"{jax.devices()[0].platform} "
                  "(substrate does not enter a learning-outcome comparison)",
        "rounds": a.resnet_rounds,
        "ours_final_acc": round(ours_acc, 4),
        "ref_final_acc": round(ref_acc, 4),
        "abs_gap": round(abs(ours_acc - ref_acc), 4),
        # asymmetric on purpose: ours must MATCH OR BEAT the reference's
        # learning outcome. GroupNorm legitimately converges faster than
        # BatchNorm under FedAvg (running-stats averaging is the known BN
        # pathology in FL — the reference's own benchmark switched to
        # ResNet-18-GN for fed_cifar100 for the same reason), and faster
        # convergence is not a parity failure.
        "criterion": "ours_final_acc >= ref_final_acc - 0.05 and > 0.5",
        "ok": ours_acc >= ref_acc - 0.05 and ours_acc > 0.5,
    }
    print(json.dumps({"experiment": "resnet56_fedavg_curve",
                      **results["resnet56_fedavg_curve"]}))


def _finish(a, results, t0):
    out = {
        "config": {
            "clients": a.clients, "per_round": a.per_round,
            "rounds": a.rounds, "epochs": a.epochs, "lr": a.lr,
            "data": "class-conditional Gaussians, MNIST/CIFAR shapes, seed 0",
            "substrate": "LR legs: both stacks on CPU (torch CPU vs XLA "
                         "CPU); ResNet curve leg: see its oracle note",
        },
        "all_ok": all(v.get("ok") for v in results.values()),
        "results": results,
        "elapsed_s": round(time.time() - t0, 1),
    }
    with open(a.out, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps({"parity": "done", "all_ok": out["all_ok"],
                      "out": a.out, "elapsed_s": out["elapsed_s"]}))
    sys.exit(0 if out["all_ok"] else 1)


if __name__ == "__main__":
    main()
