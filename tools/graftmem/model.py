"""Retention-model extraction for the M-rules.

Builds on graftiso's :class:`~tools.graftiso.model.ServingModel` (serving
classes + the handler/worker closure) and adds the three facts the memory
rules need:

1. **The analyzed universe.** Serving-class families (writes scoped to
   the handler closure) PLUS *world-root* classes (``*World*``/``*Scope``
   — graftiso's sanctioned state owners must have bounded state too) PLUS
   *serving-helper* classes, to a fixpoint: any scanned class that an
   analyzed class (a) constructs and binds to ``self.attr``, (b) obtains
   from a module factory whose body constructs it
   (``self.trace = tracing.tracer_for(...)`` → ``Tracer``), or (c)
   constructs locally and passes into an analyzed class's constructor
   (``trainer = TrainerDistAdapter(...); ClientMasterManager(args,
   trainer)``). Helper methods are analyzed in full — they run on behalf
   of handler code the closure can't see across the module boundary.
2. **Container inventory.** Per analyzed family: every mutable container
   attr (``self.x = {}``/``[]``/``set()``/``deque()``/ctor), whether it
   is *bounded by construction* (``deque(maxlen=...)``, a
   ``Bounded*``/``LRU*``/``Ring*``/``TTL*``-named ctor), and its
   annotation text (the M005 ``Message`` signal).
3. **Lifecycle facts**, computed lazily per (family, attr): eviction
   sites (``.pop/.popitem/.clear/.remove/.discard/.popleft``,
   ``del self.x[...]``, reassignment to a fresh empty container outside
   ``__init__`` — including the tuple-unpack drain idiom
   ``entries, self._entries = self._entries, []``), release sites
   (``self.x = None``), and whether a site's method is reachable from a
   shutdown/finish/resync-named method over family self-calls.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from ..graftlint.analyzer import (
    Analyzer,
    FuncInfo,
    ModuleInfo,
    _walk_shallow,
    dotted,
)
from ..graftiso.model import (
    CONTAINER_CTORS,
    SHUTDOWN_TOKENS,
    ServingModel,
    build_model as build_serving_model,
)

# ctor-name tokens that make a container bounded by construction
BOUNDED_CTOR_TOKENS = ("bounded", "lru", "ring", "ttl")

# world-root classes join the analyzed universe: graftiso sanctions them
# as state owners, so their state is exactly what must stay bounded
WORLD_ROOT_TOKENS = ("World", "Scope")

# methods that shrink a container
EVICT_METHODS = {"pop", "popitem", "clear", "remove", "discard", "popleft"}

# method-name tokens rooting the drain-reachability BFS (M004): the
# shutdown family plus the lifecycle edges the serving plane drains on
DRAIN_ROOT_TOKENS = SHUTDOWN_TOKENS + ("finish", "resync", "drain",
                                       "flush", "commit", "reset")

_DICT_CTORS = {"dict", "defaultdict", "OrderedDict", "Counter"}


def container_kind(v: ast.expr) -> Optional[Tuple[str, bool]]:
    """``(kind, bounded_by_construction)`` for a container-valued expr."""
    if isinstance(v, (ast.Dict, ast.DictComp)):
        return ("dict", False)
    if isinstance(v, (ast.List, ast.ListComp)):
        return ("list", False)
    if isinstance(v, (ast.Set, ast.SetComp)):
        return ("set", False)
    if isinstance(v, ast.Call):
        ds = dotted(v.func)
        if not ds:
            return None
        tail = ds.split(".")[-1]
        if tail in _DICT_CTORS:
            return ("dict", False)
        if tail == "list":
            return ("list", False)
        if tail == "set":
            return ("set", False)
        if tail == "deque":
            bounded = any(kw.arg == "maxlen" and not (
                isinstance(kw.value, ast.Constant) and kw.value.value is None)
                for kw in v.keywords)
            if not bounded and len(v.args) >= 2:
                bounded = True  # deque(iterable, maxlen)
            return ("deque", bounded)
        if tail[:1].isupper() and any(
                tok in tail.lower() for tok in BOUNDED_CTOR_TOKENS):
            return ("dict", True)
    return None


@dataclasses.dataclass
class ContainerInfo:
    module: str          # defining module name
    cls: str             # defining class name
    attr: str
    line: int
    kind: str            # "dict" | "list" | "set" | "deque"
    bounded: bool        # bounded by construction
    annotation: str = ""  # AnnAssign annotation text, "" when absent


@dataclasses.dataclass
class LifecycleFacts:
    """Eviction/release facts for one (family, attr), family-wide."""
    evict_sites: List[FuncInfo] = dataclasses.field(default_factory=list)
    release_sites: List[FuncInfo] = dataclasses.field(default_factory=list)

    @property
    def has_eviction(self) -> bool:
        return bool(self.evict_sites)

    @property
    def has_release(self) -> bool:
        return bool(self.release_sites)


def _self_attr(e: ast.expr) -> Optional[str]:
    if (isinstance(e, ast.Attribute) and isinstance(e.value, ast.Name)
            and e.value.id == "self"):
        return e.attr
    return None


def subscript_base_attr(t: ast.expr) -> Tuple[Optional[str], List[ast.expr]]:
    """``self.a["x"][k]`` → ("a", [key exprs outer→inner]); (None, [])
    when the base is not a self attr."""
    keys: List[ast.expr] = []
    while isinstance(t, ast.Subscript):
        keys.append(t.slice)
        t = t.value
    return _self_attr(t), keys


def _empty_container(v: ast.expr) -> bool:
    if isinstance(v, (ast.Dict, ast.List, ast.Set)):
        return not getattr(v, "keys", None) and not getattr(v, "elts", None)
    ck = container_kind(v)
    if ck is None:
        return False
    if isinstance(v, ast.Call) and not v.args:
        return True
    return False


class RetentionModel:
    def __init__(self, modules: Dict[str, ModuleInfo], lint: Analyzer,
                 serving: ServingModel):
        self.modules = modules
        self.lint = lint
        self.serving = serving
        # (module, class) of every class whose state the M-rules police
        self.analyzed_classes: Set[Tuple[str, str]] = set()
        self.helper_classes: Set[Tuple[str, str]] = set()
        # (module, class, attr) -> ContainerInfo, keyed by defining class
        self.containers: Dict[Tuple[str, str, str], ContainerInfo] = {}
        self._facts_cache: Dict[Tuple[str, str, str], LifecycleFacts] = {}
        self._drain_cache: Dict[Tuple[str, str], Set[int]] = {}
        self._build()

    # -- universe ------------------------------------------------------------

    def _build(self) -> None:
        work: Set[Tuple[str, str]] = set(self.serving.serving_classes)
        for mod in self.modules.values():
            for cls in mod.classes:
                if any(tok in cls for tok in WORLD_ROOT_TOKENS):
                    for fam in self.serving.family(mod.name, cls):
                        work.add(fam)
        self.analyzed_classes = set(work)
        # helper fixpoint
        while True:
            new = self._expand_helpers() - self.analyzed_classes
            if not new:
                break
            self.analyzed_classes |= new
            self.helper_classes |= new
        self._inventory_containers()

    def _resolve_class_name(self, mod: ModuleInfo,
                            name: str) -> Optional[Tuple[str, str]]:
        if name in mod.classes:
            return (mod.name, name)
        fi = mod.from_imports.get(name)
        if fi:
            target = self.modules.get(fi[0])
            if target and fi[1] in target.classes:
                return (fi[0], fi[1])
            # re-export hop (package __init__)
            resolved = self.serving._follow_export(fi[0], fi[1])
            if resolved is not None:
                return resolved
        return None

    def _ctor_class(self, mod: ModuleInfo,
                    call: ast.Call) -> Optional[Tuple[str, str]]:
        """The scanned class a ``Ctor(...)`` call constructs, if any."""
        ds = dotted(call.func)
        if not ds:
            return None
        parts = ds.split(".")
        if len(parts) == 1:
            return self._resolve_class_name(mod, parts[0])
        tgt = mod.imports.get(parts[0])
        if tgt and tgt in self.modules and len(parts) == 2:
            target = self.modules[tgt]
            if parts[1] in target.classes:
                return (tgt, parts[1])
        return None

    def _factory_classes(self, mod: ModuleInfo, fi: Optional[FuncInfo],
                         call: ast.Call) -> List[Tuple[str, str]]:
        """Classes constructed inside a resolvable factory call's body
        (``tracing.tracer_for(...)`` → ``Tracer``)."""
        targets: List[FuncInfo] = []
        func = call.func
        if isinstance(func, ast.Name):
            targets = self.lint.resolve_name(mod, fi, func.id)
        elif isinstance(func, ast.Attribute) and isinstance(
                func.value, ast.Name):
            tgt = mod.imports.get(func.value.id)
            if tgt is None and func.value.id in mod.from_imports:
                b, orig = mod.from_imports[func.value.id]
                full = f"{b}.{orig}" if b else orig
                tgt = full if full in self.modules else None
            if tgt and tgt in self.modules:
                target = self.modules[tgt]
                if func.attr in target.toplevel:
                    targets = [target.toplevel[func.attr]]
        out: List[Tuple[str, str]] = []
        for tf in targets:
            for node in _walk_shallow(tf.node):
                if isinstance(node, ast.Call):
                    c = self._ctor_class(tf.module, node)
                    if c is not None:
                        out.append(c)
        return out

    def _expand_helpers(self) -> Set[Tuple[str, str]]:
        found: Set[Tuple[str, str]] = set()
        for mod_name, cls in list(self.analyzed_classes):
            mod = self.modules.get(mod_name)
            if mod is None:
                continue
            for fi in mod.classes.get(cls, {}).values():
                found |= self._helper_edges(mod, fi)
        # edge (c): local ctor passed into an analyzed class's constructor,
        # anywhere in the scanned tree (runner glue lives outside classes)
        for mod in self.modules.values():
            for fi in mod.funcs_by_node.values():
                found |= self._arg_helper_edges(mod, fi)
        expanded: Set[Tuple[str, str]] = set()
        for key in found:
            for fam in self.serving.family(*key):
                expanded.add(fam)
        return expanded

    def _helper_edges(self, mod: ModuleInfo,
                      fi: FuncInfo) -> Set[Tuple[str, str]]:
        out: Set[Tuple[str, str]] = set()
        for node in _walk_shallow(fi.node):
            targets: List[ast.expr] = []
            value: Optional[ast.expr] = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            if value is None or not isinstance(value, ast.Call):
                continue
            if not any(_self_attr(t) for t in targets):
                continue
            c = self._ctor_class(mod, value)
            if c is not None:
                out.add(c)
                continue
            for fc in self._factory_classes(mod, fi, value):
                out.add(fc)
        return out

    def _arg_helper_edges(self, mod: ModuleInfo,
                          fi: FuncInfo) -> Set[Tuple[str, str]]:
        out: Set[Tuple[str, str]] = set()
        local_ctors: Dict[str, Tuple[str, str]] = {}
        for node in _walk_shallow(fi.node):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)):
                c = self._ctor_class(mod, node.value)
                if c is not None:
                    local_ctors[node.targets[0].id] = c
        if not local_ctors:
            return out
        for node in _walk_shallow(fi.node):
            if not isinstance(node, ast.Call):
                continue
            c = self._ctor_class(mod, node)
            if c is None or c not in self.analyzed_classes:
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name) and arg.id in local_ctors:
                    out.add(local_ctors[arg.id])
        return out

    # -- container inventory -------------------------------------------------

    def _inventory_containers(self) -> None:
        for mod_name, cls in self.analyzed_classes:
            mod = self.modules.get(mod_name)
            if mod is None:
                continue
            for fi in mod.classes.get(cls, {}).values():
                for node in _walk_shallow(fi.node):
                    targets: List[ast.expr] = []
                    value: Optional[ast.expr] = None
                    ann = ""
                    if isinstance(node, ast.Assign):
                        targets, value = node.targets, node.value
                    elif isinstance(node, ast.AnnAssign):
                        targets, value = [node.target], node.value
                        try:
                            ann = ast.unparse(node.annotation)
                        except Exception:  # pragma: no cover - exotic ann
                            ann = ""
                    for t in targets:
                        attr = _self_attr(t)
                        if attr is None:
                            continue
                        key = (mod_name, cls, attr)
                        if value is not None:
                            ck = container_kind(value)
                            if ck is not None:
                                prev = self.containers.get(key)
                                if prev is None:
                                    self.containers[key] = ContainerInfo(
                                        mod_name, cls, attr, node.lineno,
                                        ck[0], ck[1], ann)
                                elif ck[1]:
                                    prev.bounded = True
                                continue
                        if ann and key not in self.containers \
                                and "Message" in ann:
                            # Message-typed attr with a non-container
                            # initializer (usually None): M005 inventory
                            self.containers[key] = ContainerInfo(
                                mod_name, cls, attr, node.lineno,
                                "ref", False, ann)

    def find_container(self, mod_name: str, cls: str,
                       attr: str) -> Optional[ContainerInfo]:
        for m, c in self.serving.family(mod_name, cls):
            info = self.containers.get((m, c, attr))
            if info is not None:
                return info
        return None

    # -- analyzed functions --------------------------------------------------

    def is_analyzed(self, fi: FuncInfo) -> bool:
        """Growth-site scope: closure functions of serving classes, every
        method of helper/world-root classes, plus nested defs thereof."""
        f = fi
        while f is not None and f.class_name is None and f.parent is not None:
            f = f.parent
        if f is None or f.class_name is None:
            return fi in self.serving.closure
        key = (f.module.name, f.class_name)
        if key not in self.analyzed_classes:
            return False
        if key in self.serving.serving_classes:
            return fi in self.serving.closure or f in self.serving.closure
        return True

    def family_methods(self, mod_name: str, cls: str) -> List[FuncInfo]:
        out: List[FuncInfo] = []
        for m, c in self.serving.family(mod_name, cls):
            mod = self.modules.get(m)
            if mod is None:
                continue
            out.extend(mod.classes.get(c, {}).values())
        return out

    # -- lifecycle facts -----------------------------------------------------

    def facts(self, mod_name: str, cls: str, attr: str) -> LifecycleFacts:
        key = (mod_name, cls, attr)
        cached = self._facts_cache.get(key)
        if cached is not None:
            return cached
        facts = LifecycleFacts()
        for fi in self.family_methods(mod_name, cls):
            if self._method_evicts(fi, attr):
                facts.evict_sites.append(fi)
            if self._method_releases(fi, attr):
                facts.release_sites.append(fi)
        self._facts_cache[key] = facts
        return facts

    @staticmethod
    def _method_evicts(fi: FuncInfo, attr: str) -> bool:
        is_init = fi.name == "__init__" if hasattr(fi, "name") else False
        for node in _walk_shallow(fi.node):
            if isinstance(node, ast.Call):
                f = node.func
                if (isinstance(f, ast.Attribute)
                        and f.attr in EVICT_METHODS
                        and _self_attr(f.value) == attr):
                    return True
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    base, keys = subscript_base_attr(t)
                    if keys and base == attr:
                        return True
                    if not keys and _self_attr(t) == attr:
                        return True
            elif isinstance(node, ast.Assign):
                # reassignment to a fresh empty container outside __init__
                # (reset/drain), incl. the tuple-unpack drain idiom
                for t, v in _assign_pairs(node):
                    if _self_attr(t) == attr and _empty_container(v) \
                            and not is_init \
                            and fi.qualname.rsplit(".", 1)[-1] != "__init__":
                        return True
        return False

    @staticmethod
    def _method_releases(fi: FuncInfo, attr: str) -> bool:
        for node in _walk_shallow(fi.node):
            if isinstance(node, ast.Assign):
                for t, v in _assign_pairs(node):
                    if (_self_attr(t) == attr
                            and isinstance(v, ast.Constant)
                            and v.value is None):
                        return True
        return False

    # -- drain reachability (M004) -------------------------------------------

    def drain_reachable(self, mod_name: str, cls: str) -> Set[int]:
        """ids of FuncInfos reachable from a shutdown/finish/resync-named
        family method over ``self.*`` calls."""
        key = (mod_name, cls)
        cached = self._drain_cache.get(key)
        if cached is not None:
            return cached
        seeds: List[FuncInfo] = []
        for fi in self.family_methods(mod_name, cls):
            name = fi.qualname.rsplit(".", 1)[-1]
            if any(tok in name.lower() for tok in DRAIN_ROOT_TOKENS):
                seeds.append(fi)
        seen: Set[int] = set()
        out: List[FuncInfo] = []
        work = list(seeds)
        while work:
            fi = work.pop()
            if id(fi) in seen:
                continue
            seen.add(id(fi))
            out.append(fi)
            work.extend(fi.nested.values())
            for node in _walk_shallow(fi.node):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "self"):
                    t = self.serving.family_method(mod_name, cls,
                                                   node.func.attr)
                    if t is not None:
                        work.append(t)
        self._drain_cache[key] = seen
        return seen

    def drains_on_shutdown(self, mod_name: str, cls: str,
                           attr: str) -> bool:
        reachable = self.drain_reachable(mod_name, cls)
        return any(id(fi) in reachable
                   for fi in self.facts(mod_name, cls, attr).evict_sites)


def _assign_pairs(node: ast.Assign) -> List[Tuple[ast.expr, ast.expr]]:
    """(target, value) pairs, unzipping parallel tuple assignment."""
    out: List[Tuple[ast.expr, ast.expr]] = []
    for t in node.targets:
        if (isinstance(t, ast.Tuple) and isinstance(node.value, ast.Tuple)
                and len(t.elts) == len(node.value.elts)):
            out.extend(zip(t.elts, node.value.elts))
        else:
            out.append((t, node.value))
    return out


def build_model(modules: Dict[str, ModuleInfo],
                lint: Analyzer) -> RetentionModel:
    serving = build_serving_model(modules, lint)
    return RetentionModel(modules, lint, serving)
