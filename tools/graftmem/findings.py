"""graftmem rule registry (M001–M005), merged into the shared graftlint
Finding infrastructure so all six suites render/baseline/JSON identically.

The M-rules statically enforce the serving plane's memory contract — the
prerequisite for multi-tenant serving and the 50k–100k device soak
(ROADMAP): every piece of state a handler/worker can grow must be
provably bounded (capacity ring, clear-on-commit, TTL/LRU eviction) or
released when the lifecycle that needed it ends. The runtime witness is
``fedml_tpu swarm --leak_check`` (RSS steady-state slope + ``mem.*``
occupancy gauges) — docs/graftmem.md pins the two ends together.
"""

from __future__ import annotations

from typing import Dict, Tuple

from ..graftlint.findings import Finding, register_rules

# rule id -> (title, autofix hint)
MEM_RULES: Dict[str, Tuple[str, str]] = {
    "M001": (
        "unbounded-keyed-growth",
        "bound the container: BoundedDict/deque(maxlen=...) with a "
        "generous capacity, a ring check (while len > capacity: del "
        "oldest), clear-on-commit/finish for per-round state, or clamp "
        "the key into a finite domain (min(k, CAP)) — a dict keyed by "
        "sender/round data with no eviction is a slow OOM at a million "
        "clients",
    ),
    "M002": (
        "capacity-less-cache",
        "give the cache a size bound (BoundedDict(capacity), LRU, or an "
        "explicit ring sweep): memo/negative caches keyed by data grow "
        "with the key domain, and a compile/encode cache that never "
        "evicts pins every variant it ever saw",
    ),
    "M003": (
        "telemetry-cardinality-explosion",
        "keep metric NAMES to a fixed vocabulary and carry the variable "
        "as the value (or a clamped bucket): interpolating a client/"
        "round/version id into the name grows the process-wide registry "
        "by one series per distinct id, forever",
    ),
    "M004": (
        "undrained-parking",
        "drain parked/pending/deferred containers from a shutdown/finish/"
        "resync-reachable method (.clear() in the close path, or pop on "
        "lease expiry): parked entries that only drain on the happy path "
        "survive the federation that parked them",
    ),
    "M005": (
        "payload-retention-past-commit",
        "release message/payload references when their round commits or "
        "the federation finishes (self.attr = None in the finish/commit "
        "path): a retained decoded frame pins the whole payload buffer "
        "for the life of the manager",
    ),
}

register_rules(MEM_RULES)

__all__ = ["Finding", "MEM_RULES"]
