"""graftmem entry: scan → graftlint facts → retention model → M-rules →
pragmas.

Mirrors :func:`tools.graftiso.analyzer.analyze_paths`, with graftmem's own
pragma marker (``# graftmem: disable=M001``) and baseline file
(``tools/graftmem/baseline.json``). The whole pass is pure AST — no import
of the analyzed code, no jax — so the tree gate stays sub-second. The
runtime witness for the same contract is ``fedml_tpu swarm --leak_check``
(RSS steady-state slope + ``mem.*`` occupancy gauges).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

from ..graftlint.analyzer import Analyzer, collect_files, load_modules
from ..graftlint.baseline import find_repo_root
from ..graftlint.pragmas import is_suppressed, parse_pragmas
from .findings import Finding
from .model import RetentionModel, build_model
from .rules import check_retention

PRAGMA_TOOL = "graftmem"
DEFAULT_BASELINE_RELPATH = os.path.join("tools", "graftmem", "baseline.json")


def default_baseline_path(repo_root: str) -> str:
    return os.path.join(repo_root, DEFAULT_BASELINE_RELPATH)


def analyze_paths_with_model(
    paths: Sequence[str], repo_root: Optional[str] = None
) -> Tuple[List[Finding], RetentionModel]:
    """Analyze files/dirs → (pragma-filtered findings, retention model).

    The baseline is NOT applied here — that's the CLI/caller's job, like
    the sibling suites.
    """
    if repo_root is None:
        repo_root = find_repo_root(paths[0] if paths else os.getcwd())
    files = collect_files(paths)
    modules = load_modules(files, repo_root)
    lint = Analyzer(modules)
    lint.compute_facts()
    model = build_model(modules, lint)
    findings = check_retention(modules, lint, model)

    out: List[Finding] = []
    pragma_cache: Dict[str, Dict] = {}
    mods_by_rel = {m.rel: m for m in modules.values()}
    for f in findings:
        mod = mods_by_rel.get(f.path)
        if mod is not None:
            pragmas = pragma_cache.setdefault(
                f.path, parse_pragmas(mod.source, tool=PRAGMA_TOOL))
            if is_suppressed(pragmas, f.rule, f.line):
                continue
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out, model


def analyze_paths(paths: Sequence[str],
                  repo_root: Optional[str] = None) -> List[Finding]:
    return analyze_paths_with_model(paths, repo_root)[0]
