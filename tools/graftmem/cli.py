"""graftmem CLI: ``python -m tools.graftmem [paths...]``.

Thin suite definition over the shared driver
(:mod:`tools.graftlint.clikit` — flags, baseline handling, rendering, and
the exit-code contract live there, shared with the five sibling suites).
Exit codes: 0 clean (after baseline + pragmas), 1 findings, 2 usage error
OR analyzer crash.

The default (and only) pass is pure AST — graftmem's runtime mode lives
in the swarm harness instead: ``fedml_tpu swarm --leak_check`` samples
RSS + the ``mem.*`` occupancy gauges across a soak and fails on a
positive steady-state slope (docs/graftmem.md), so the static rules and
the runtime gate pin each other.
"""

from __future__ import annotations

import argparse
from typing import Dict, List, Optional, Tuple

from ..graftlint import clikit
from ..graftlint.findings import Finding
from .analyzer import DEFAULT_BASELINE_RELPATH, analyze_paths_with_model
from .findings import MEM_RULES


def _analyze(args: argparse.Namespace,
             repo_root: str) -> Tuple[List[Finding], Dict]:
    findings, model = analyze_paths_with_model(args.paths,
                                               repo_root=repo_root)
    extra: Dict = {
        "mem": {
            "classes": sorted(f"{m}.{c}"
                              for m, c in model.analyzed_classes),
            "helpers": sorted(f"{m}.{c}"
                              for m, c in model.helper_classes),
            "containers": len(model.containers),
            "closure_size": len(model.serving.closure),
        },
    }
    return findings, extra


def main(argv: Optional[List[str]] = None) -> int:
    return clikit.run_suite(
        argv,
        tool="graftmem",
        description="static unbounded-state & retention verification of "
                    "the serving plane: keyed growth without eviction, "
                    "capacity-less caches, telemetry cardinality "
                    "explosions, undrained parking containers, payload "
                    "retention past commit",
        rules=MEM_RULES,
        analyze=_analyze,
        baseline_relpath=DEFAULT_BASELINE_RELPATH,
    )


if __name__ == "__main__":
    raise SystemExit(main())
