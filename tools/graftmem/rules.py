"""Rule checkers M001–M005 over the :class:`~tools.graftmem.model.RetentionModel`.

The M-rules statically enforce the serving plane's memory contract
(docs/graftmem.md):

- **M001** unbounded keyed growth: a container attr written from
  handler/worker/helper code with a key (or appended value) derived from
  message/sender/round data — sender ids, round indices, versions, peer
  ranks — and no reachable eviction in the owning family.
- **M002** capacity-less cache: memo/negative-cache attrs
  (``*cache*``/``*memo*``/``*jit*``/``*compiled*``) with no size bound
  and no eviction.
- **M003** telemetry cardinality explosion: message/round-derived values
  interpolated into metric NAMES (f-string/``%``/``+``/``.format``), one
  registry series per distinct id, forever.
- **M004** undrained parking: parked/pending/deferred containers whose
  drain is not reachable from a shutdown/finish/resync-named method —
  happy-path-only drains survive the federation that parked them.
- **M005** payload retention past commit: ``Message``-typed attrs (or
  attrs assigned a constructed ``Message``) with no release site
  (``self.attr = None``) in the owning family.

Accepted boundedness idioms (the dogfooded tree uses all of them, see
docs/graftmem.md): ``deque(maxlen=...)``; ``Bounded*``/``LRU*``/
``Ring*``/``TTL*``-named ctors; a ring check (``while len(...) >
capacity: del self.x[oldest]``); ``.pop/.discard`` lifecycle eviction;
clear-on-commit/finish (``.clear()`` or reassignment to a fresh empty
container outside ``__init__``, including the tuple-unpack drain
``entries, self._entries = self._entries, []``); and clamped keys
(``min(k, CAP)``-shaped — a finite key domain needs no eviction).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..graftlint.analyzer import (
    Analyzer,
    FuncInfo,
    ModuleInfo,
    _walk_shallow,
    dotted,
)
from .findings import Finding
from .model import (
    RetentionModel,
    _assign_pairs,
    _self_attr,
    subscript_base_attr,
)

# identifier/string tokens marking a value as message/sender/round-derived
TAINT_TOKENS = ("sender", "client", "round", "version", "peer", "rank",
                "edge", "msg", "message", "seq", "stalen", "epoch",
                "tenant", "uuid")

# attr-name tokens marking a memoization/negative cache (M002)
CACHE_TOKENS = ("cache", "memo", "jit", "compiled", "interned")

# attr-name tokens marking a parking container (M004)
PARKING_TOKENS = ("pending", "parked", "defer", "inflight", "backlog",
                  "unsent", "queued", "waiting")

# call-name tails that create/update a telemetry series (M003)
TELEMETRY_TAILS = {"counter_inc", "gauge_set", "observe", "inc"}

# growth mutators and the argument that acts as the key/value
_KEYED_MUTATORS = {"setdefault": 0, "add": 0}
_VALUE_MUTATORS = {"append": 0, "appendleft": 0, "extend": 0, "update": 0}


def _mk(mod: ModuleInfo, rule: str, line: int, col: int,
        message: str) -> Finding:
    return Finding(rule=rule, path=mod.rel, line=line, col=col,
                   message=message, line_text=mod.line_text(line))


def _local_aliases(fi: FuncInfo) -> Dict[str, ast.expr]:
    """local name -> last assigned value expr (one-level resolution)."""
    out: Dict[str, ast.expr] = {}
    for node in _walk_shallow(fi.node):
        if isinstance(node, ast.Assign):
            for t, v in _assign_pairs(node):
                if isinstance(t, ast.Name):
                    out[t.id] = v
    return out


def _is_clamp_call(node: ast.Call) -> bool:
    ds = dotted(node.func) or ""
    tail = ds.split(".")[-1].lower()
    if tail == "min" and len(node.args) >= 2:
        return True
    return "clamp" in tail or "bucket" in tail


def _token_match(text: str) -> bool:
    low = text.lower()
    return any(tok in low for tok in TAINT_TOKENS)


def tainted(expr: ast.expr, aliases: Dict[str, ast.expr],
            depth: int = 0) -> bool:
    """The expr carries message/sender/round-derived data: a taint-token
    identifier, attribute, or string constant — unless the whole value is
    clamped into a finite domain (``min(k, CAP)``/``*clamp*``/``*bucket*``
    call)."""
    if depth > 3:
        return False
    for node in ast.walk(expr):
        if isinstance(node, ast.Call) and _is_clamp_call(node):
            return False
    for node in ast.walk(expr):
        if isinstance(node, ast.Name):
            if _token_match(node.id):
                return True
            alias = aliases.get(node.id)
            if alias is not None and alias is not expr:
                if tainted(alias, aliases, depth + 1):
                    return True
        elif isinstance(node, ast.Attribute) and _token_match(node.attr):
            return True
        elif (isinstance(node, ast.Constant)
              and isinstance(node.value, str) and _token_match(node.value)):
            return True
    return False


class _WriteSite:
    __slots__ = ("mod", "fi", "line", "col", "attr", "keys", "via")

    def __init__(self, mod: ModuleInfo, fi: FuncInfo, line: int, col: int,
                 attr: str, keys: List[ast.expr], via: str):
        self.mod = mod
        self.fi = fi
        self.line = line
        self.col = col
        self.attr = attr
        self.keys = keys
        self.via = via


def _collect_writes(mod: ModuleInfo, fi: FuncInfo) -> List[_WriteSite]:
    """Growth writes to ``self.*`` containers in one function."""
    out: List[_WriteSite] = []
    for node in _walk_shallow(fi.node):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if isinstance(t, ast.Tuple):
                    continue  # parallel assignment: drain idiom, not growth
                base, keys = subscript_base_attr(t)
                if base is not None and keys:
                    out.append(_WriteSite(mod, fi, node.lineno,
                                          node.col_offset, base, keys,
                                          "subscript write"))
        elif isinstance(node, ast.Call):
            f = node.func
            if not isinstance(f, ast.Attribute):
                continue
            attr = _self_attr(f.value)
            if attr is None:
                continue
            if f.attr in _KEYED_MUTATORS and node.args:
                out.append(_WriteSite(mod, fi, node.lineno,
                                      node.col_offset, attr,
                                      [node.args[_KEYED_MUTATORS[f.attr]]],
                                      f".{f.attr}(...)"))
            elif f.attr in _VALUE_MUTATORS and node.args:
                out.append(_WriteSite(mod, fi, node.lineno,
                                      node.col_offset, attr,
                                      list(node.args),
                                      f".{f.attr}(...)"))
    return out


# ---------------------------------------------------------------------------
# M001 / M002 / M004 — container growth vs. eviction
# ---------------------------------------------------------------------------


def _is_cache_attr(attr: str) -> bool:
    return any(tok in attr.lower() for tok in CACHE_TOKENS)


def _is_parking_attr(attr: str) -> bool:
    return any(tok in attr.lower() for tok in PARKING_TOKENS)


def _family_has_write(model: RetentionModel, mod_name: str, cls: str,
                      attr: str) -> Optional[_WriteSite]:
    for fi in model.family_methods(mod_name, cls):
        if fi.qualname.rsplit(".", 1)[-1] == "__init__":
            continue
        for w in _collect_writes(fi.module, fi):
            if w.attr == attr:
                return w
    return None


def check_growth(model: RetentionModel) -> List[Finding]:
    """M001/M002/M004 in one pass so each attr yields ONE finding, the
    most specific rule first (cache > parking > keyed growth)."""
    findings: List[Finding] = []
    claimed: Set[Tuple[str, str, str]] = set()

    # M002: definition-driven — a cache-named container must be bounded
    for (mod_name, cls, attr), info in sorted(model.containers.items()):
        if info.kind == "ref" or not _is_cache_attr(attr):
            continue
        if info.bounded:
            continue
        facts = model.facts(mod_name, cls, attr)
        if facts.has_eviction:
            continue
        w = _family_has_write(model, mod_name, cls, attr)
        if w is None:
            continue
        claimed.add((mod_name, cls, attr))
        mod = model.modules[mod_name]
        findings.append(_mk(
            mod, "M002", info.line, 0,
            f"cache `{cls}.{attr}` has no size bound and no eviction — "
            f"it is written in `{w.fi.qualname}` and keeps every variant "
            "it ever saw; give it a capacity (BoundedDict/LRU/ring "
            "sweep)"))

    # M004: parking-named containers must drain from the shutdown path
    for (mod_name, cls, attr), info in sorted(model.containers.items()):
        key = (mod_name, cls, attr)
        if key in claimed or info.kind == "ref":
            continue
        if not _is_parking_attr(attr) or info.bounded:
            continue
        w = _family_has_write(model, mod_name, cls, attr)
        if w is None:
            continue
        if model.drains_on_shutdown(mod_name, cls, attr):
            continue
        claimed.add(key)
        mod = model.modules[mod_name]
        facts = model.facts(mod_name, cls, attr)
        how = ("its only drains are happy-path" if facts.has_eviction
               else "it is never drained at all")
        findings.append(_mk(
            mod, "M004", info.line, 0,
            f"parking container `{cls}.{attr}` — {how}: no drain is "
            "reachable from a shutdown/finish/resync method, so parked "
            "entries survive the federation that parked them; clear it "
            "in the close/finish path"))

    # M001: tainted-key growth without eviction, write-site driven
    reported: Set[Tuple[str, str, str]] = set()
    for mod in model.modules.values():
        for fi in mod.funcs_by_node.values():
            if not model.is_analyzed(fi):
                continue
            owner = _owning_class(fi)
            if owner is None:
                continue
            aliases = _local_aliases(fi)
            for w in _collect_writes(mod, fi):
                info = model.find_container(owner[0], owner[1], w.attr)
                if info is None or info.bounded or info.kind == "ref":
                    continue
                key = (info.module, info.cls, info.attr)
                if key in claimed or key in reported:
                    continue
                # a bare string-constant key is ONE fixed slot, not a
                # growth axis (self._stats["folds"] += 1)
                live_keys = [k for k in w.keys
                             if not isinstance(k, ast.Constant)]
                if not any(tainted(k, aliases) for k in live_keys):
                    continue
                facts = model.facts(owner[0], owner[1], w.attr)
                if facts.has_eviction:
                    continue
                reported.add(key)
                findings.append(_mk(
                    mod, "M001", w.line, w.col,
                    f"`{info.cls}.{w.attr}` grows by message/round-derived "
                    f"key via {w.via} in `{fi.qualname}` with no eviction "
                    "anywhere in the owning family — one entry per "
                    "distinct sender/round, forever; bound it or clear it "
                    "on commit"))
    return findings


def _owning_class(fi: FuncInfo) -> Optional[Tuple[str, str]]:
    f = fi
    while f is not None and f.class_name is None:
        f = f.parent
    if f is None or f.class_name is None:
        return None
    return (f.module.name, f.class_name)


# ---------------------------------------------------------------------------
# M003 — telemetry cardinality explosion
# ---------------------------------------------------------------------------


def _dynamic_name_taint(expr: ast.expr,
                        aliases: Dict[str, ast.expr]) -> Optional[str]:
    """Why a metric-name expr has unbounded cardinality, or None."""
    if isinstance(expr, ast.JoinedStr):
        for v in expr.values:
            if isinstance(v, ast.FormattedValue) \
                    and tainted(v.value, aliases):
                return "f-string interpolates"
    elif isinstance(expr, ast.BinOp) and isinstance(expr.op,
                                                    (ast.Add, ast.Mod)):
        for side in (expr.left, expr.right):
            if not (isinstance(side, ast.Constant)
                    and isinstance(side.value, str)) \
                    and tainted(side, aliases):
                return "concatenation embeds"
    elif (isinstance(expr, ast.Call)
          and isinstance(expr.func, ast.Attribute)
          and expr.func.attr == "format"):
        for a in list(expr.args) + [kw.value for kw in expr.keywords]:
            if tainted(a, aliases):
                return ".format() embeds"
    return None


def check_m003(model: RetentionModel) -> List[Finding]:
    findings: List[Finding] = []
    for mod in model.modules.values():
        for fi in mod.funcs_by_node.values():
            if not model.is_analyzed(fi):
                continue
            aliases = _local_aliases(fi)
            for node in _walk_shallow(fi.node):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                ds = dotted(node.func) or ""
                if ds.split(".")[-1] not in TELEMETRY_TAILS:
                    continue
                why = _dynamic_name_taint(node.args[0], aliases)
                if why is None:
                    continue
                findings.append(_mk(
                    mod, "M003", node.lineno, node.col_offset,
                    f"metric name {why} a message/round-derived value in "
                    f"`{fi.qualname}` — the registry grows one series per "
                    "distinct id; keep names to a fixed vocabulary and "
                    "carry the id as a value or clamped bucket"))
    return findings


# ---------------------------------------------------------------------------
# M005 — payload retention past commit
# ---------------------------------------------------------------------------


def check_m005(model: RetentionModel) -> List[Finding]:
    findings: List[Finding] = []
    # annotation-declared Message attrs: a plain/Optional Message
    # reference, NOT a container OF handlers (Dict[str, MessageHandler])
    retaining: Dict[Tuple[str, str, str], Tuple[ModuleInfo, int]] = {}
    for (mod_name, cls, attr), info in model.containers.items():
        if info.kind != "ref":
            continue
        if re.search(r"\bMessage\b", info.annotation or ""):
            retaining[(mod_name, cls, attr)] = (
                model.modules[mod_name], info.line)
    # write-declared: ``self.attr = Message(...)`` (or a local bound to
    # one) in analyzed code
    for mod in model.modules.values():
        for fi in mod.funcs_by_node.values():
            if not model.is_analyzed(fi):
                continue
            owner = _owning_class(fi)
            if owner is None:
                continue
            aliases = _local_aliases(fi)
            for node in _walk_shallow(fi.node):
                if not isinstance(node, ast.Assign):
                    continue
                for t, v in _assign_pairs(node):
                    attr = _self_attr(t)
                    if attr is None:
                        continue
                    if _constructs_message(v, aliases):
                        key = (owner[0], owner[1], attr)
                        if key not in retaining:
                            retaining[key] = (mod, node.lineno)
    for (mod_name, cls, attr), (mod, line) in sorted(retaining.items()):
        facts = model.facts(mod_name, cls, attr)
        if facts.has_release:
            continue
        findings.append(_mk(
            mod, "M005", line, 0,
            f"`{cls}.{attr}` retains a Message payload with no release "
            "site (`self." + attr + " = None`) in the owning family — the "
            "decoded payload stays live after its round commits; release "
            "it in the finish/commit path"))
    return findings


def _constructs_message(v: ast.expr, aliases: Dict[str, ast.expr],
                        depth: int = 0) -> bool:
    if depth > 2:
        return False
    if isinstance(v, ast.Call):
        ds = dotted(v.func) or ""
        if ds.split(".")[-1] == "Message":
            return True
    if isinstance(v, ast.Name):
        alias = aliases.get(v.id)
        if alias is not None and alias is not v:
            return _constructs_message(alias, aliases, depth + 1)
    return False


# ---------------------------------------------------------------------------
# Entry
# ---------------------------------------------------------------------------


def check_retention(modules: Dict[str, ModuleInfo], lint: Analyzer,
                    model: RetentionModel) -> List[Finding]:
    findings: List[Finding] = []
    findings += check_growth(model)
    findings += check_m003(model)
    findings += check_m005(model)
    return findings
