"""graftmem — static unbounded-state & retention verification of the
serving plane (M001–M005), sixth suite on the shared graftlint driver.

``python -m tools.graftmem [paths...]`` — see docs/graftmem.md.
"""
