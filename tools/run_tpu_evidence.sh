#!/bin/bash
# One-shot TPU evidence run — everything round 5 could not measure because
# the axon tunnel was down (ROUND5_NOTES.md). Run on a host where
# `python -c "import jax; print(jax.devices())"` shows the TPU.
set -x
cd "$(dirname "$0")/.."
python bench.py                         # full ladder -> BENCH_PARTIAL.json
python tools/bench_ring_kernel.py       # block sweep + CP train step
python tools/check_7b_readiness.py      # v5e:8,v5e:16,v5p:32 AOT rows
git add BENCH_PARTIAL.json RING_KERNEL_BENCH.json SEVENB_READINESS.json
git commit -m "TPU evidence: bench ladder, ring sweep, 7B readiness rows"
