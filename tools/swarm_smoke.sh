#!/usr/bin/env bash
# CI smoke for the async traffic plane (fedml_tpu/traffic/, docs/traffic.md):
# two short client-swarm soaks against the FedBuff-style async server.
#
#  leg 1 (light load):  admission wide open — the soak must complete every
#     server step with ZERO shed updates and report a p99 dispatch→ready
#     latency from the telemetry histogram.
#  leg 2 (overload):    a starved token bucket — the soak must SHED
#     (nonzero traffic.shed_updates), still complete every step through
#     the clients' NACK-retry-after re-offers, and hold peak RSS bounded
#     (overload degrades to load-shedding, not memory growth).
#
# This is the executable form of the traffic-plane contract;
# tests/test_traffic.py is the fine-grained half.
#
# Usage: tools/swarm_smoke.sh          (CI: exits non-zero on any regression)
set -uo pipefail
cd "$(dirname "$0")/.."

run_leg() {
    timeout -k 10 240 env JAX_PLATFORMS=cpu \
        python -m fedml_tpu.cli swarm "$@" 2>/dev/null
}

light=$(run_leg --clients 40 --steps 5 --buffer 8 --think_s 0.02 \
    --seed 7 --timeout 180 --run_id swarm-smoke-light)
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "swarm_smoke: FAIL — light-load leg exited rc=$rc" >&2
    printf '%s\n' "$light" >&2
    exit 1
fi

python - "$light" <<'EOF'
import json
import sys

r = json.loads(sys.argv[1])
assert r["ok"], r
assert r["steps_completed"] == r["steps_requested"], r
assert r["shed_updates"] == 0, f"light load shed: {r['shed_updates']}"
assert r["devices_finished"] == r["clients"], r
assert r["dispatch_ready_s"]["count"] > 0, r
assert r["dispatch_ready_s"]["p99"] is not None, r
print("swarm_smoke: light OK —",
      f"{r['clients']} devices, {r['steps_completed']} steps,",
      f"p99 dispatch→ready {1e3 * r['dispatch_ready_s']['p99']:.1f}ms,",
      f"0 shed, rss {r['rss_peak_mb']:.0f} MB")
EOF
[ $? -ne 0 ] && { echo "swarm_smoke: FAIL — light verdict" >&2; exit 1; }

over=$(run_leg --clients 40 --steps 5 --buffer 8 --think_s 0.01 \
    --admit_rate 15 --admit_burst 4 --seed 7 --timeout 180 \
    --run_id swarm-smoke-over)
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "swarm_smoke: FAIL — overload leg exited rc=$rc" >&2
    printf '%s\n' "$over" >&2
    exit 1
fi

python - "$over" <<'EOF'
import json
import sys

r = json.loads(sys.argv[1])
assert r["ok"], r
assert r["steps_completed"] == r["steps_requested"], r
assert r["shed_updates"] > 0, "overload leg shed nothing"
# bounded memory: a 40-device lr soak fits comfortably under this cap —
# unbounded queue growth (the failure mode admission control exists to
# prevent) blows straight past it
assert r["rss_peak_mb"] < 4096, f"rss {r['rss_peak_mb']} MB"
print("swarm_smoke: overload OK —",
      f"{r['shed_updates']:.0f} shed / {r['accepted_updates']:.0f} accepted,",
      f"{r['steps_completed']} steps, rss {r['rss_peak_mb']:.0f} MB")
EOF
[ $? -ne 0 ] && { echo "swarm_smoke: FAIL — overload verdict" >&2; exit 1; }

echo "swarm_smoke: PASS"
