#!/usr/bin/env bash
# CI smoke for the async traffic plane (fedml_tpu/traffic/, docs/traffic.md):
# three short client-swarm soaks against the FedBuff-style async server.
#
#  leg 1 (light load):  admission wide open — the soak must complete every
#     server step with ZERO shed updates and report a p99 dispatch→ready
#     latency from the telemetry histogram.
#  leg 2 (overload):    a starved token bucket — the soak must SHED
#     (nonzero traffic.shed_updates), still complete every step through
#     the clients' NACK-retry-after re-offers, and hold peak RSS bounded
#     (overload degrades to load-shedding, not memory growth).
#  leg 3 (grpc+delta):  a small-N soak over REAL multiprocess gRPC with
#     rank→port multiplexing (--ranks_per_port) and the S2C delta plane on
#     (s2c_delta=auto): every device-host process must exit 0, delta
#     frames must actually flow (comm.delta.s2c_delta_frames > 0), and the
#     verdict reports p99 dispatch→ready next to the loopback leg's.
#  leg 4 (device wire): the delta-plane soak again with --wire_path device
#     (docs/delivery.md device-direct wire path): the jit'd codec kernels
#     must serve the soak's encodes AND decodes (nonzero
#     comm.wire.device_encodes / device_decodes, ZERO host fallbacks)
#     while every step still completes — same protocol, different engine.
#  leg 5 (traced grpc): the multiprocess gRPC soak again with --trace
#     (docs/tracing.md): the merged cross-process trace must be orphan-
#     free with a non-empty critical path for EVERY committed round, and
#     the trace's Σ(fold + queue_wait) must reconcile with the
#     traffic.dispatch_ready_s histogram sum within 5% — two instruments,
#     one truth.
#  leg 6 (leak check): a longer loopback soak under --leak_check
#     (docs/graftmem.md, the static retention suite's runtime half): VmRSS
#     is sampled across the soak and the report's mem block must show a
#     NON-positive steady-state slope (≤ the MB/s tolerance) — a retention
#     bug (one entry per sender/round never released) is linear growth
#     under constant load by definition. The per-container mem.* occupancy
#     gauges must be present and every bounded container at or under its
#     capacity.
#  leg 7 (edge tier):  --tiers 2 at swarm scale (docs/traffic.md
#     "Hierarchical edge tier"): ~200 devices homed onto 2 edge
#     aggregators over REAL multiprocess gRPC. The root must fold ONLY
#     edge summaries (edge_tier.direct_client_updates == 0 — a nonzero
#     count means a device bypassed its home edge), summaries must
#     actually flow, every device-host process must exit 0, and world
#     shutdown must leak ZERO threads across the extra tier.
#
# This is the executable form of the traffic-plane contract;
# tests/test_traffic.py is the fine-grained half.
#
# Usage: tools/swarm_smoke.sh          (CI: exits non-zero on any regression)
set -uo pipefail
cd "$(dirname "$0")/.."

run_leg() {
    timeout -k 10 240 env JAX_PLATFORMS=cpu \
        python -m fedml_tpu.cli swarm "$@" 2>/dev/null
}

light=$(run_leg --clients 40 --steps 5 --buffer 8 --think_s 0.02 \
    --seed 7 --timeout 180 --run_id swarm-smoke-light)
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "swarm_smoke: FAIL — light-load leg exited rc=$rc" >&2
    printf '%s\n' "$light" >&2
    exit 1
fi

python - "$light" <<'EOF'
import json
import sys

r = json.loads(sys.argv[1])
assert r["ok"], r
assert r["steps_completed"] == r["steps_requested"], r
assert r["shed_updates"] == 0, f"light load shed: {r['shed_updates']}"
assert r["devices_finished"] == r["clients"], r
assert r["dispatch_ready_s"]["count"] > 0, r
assert r["dispatch_ready_s"]["p99"] is not None, r
print("swarm_smoke: light OK —",
      f"{r['clients']} devices, {r['steps_completed']} steps,",
      f"p99 dispatch→ready {1e3 * r['dispatch_ready_s']['p99']:.1f}ms,",
      f"0 shed, rss {r['rss_peak_mb']:.0f} MB")
EOF
[ $? -ne 0 ] && { echo "swarm_smoke: FAIL — light verdict" >&2; exit 1; }

over=$(run_leg --clients 40 --steps 5 --buffer 8 --think_s 0.01 \
    --admit_rate 15 --admit_burst 4 --seed 7 --timeout 180 \
    --run_id swarm-smoke-over)
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "swarm_smoke: FAIL — overload leg exited rc=$rc" >&2
    printf '%s\n' "$over" >&2
    exit 1
fi

python - "$over" <<'EOF'
import json
import sys

r = json.loads(sys.argv[1])
assert r["ok"], r
assert r["steps_completed"] == r["steps_requested"], r
assert r["shed_updates"] > 0, "overload leg shed nothing"
# bounded memory: a 40-device lr soak fits comfortably under this cap —
# unbounded queue growth (the failure mode admission control exists to
# prevent) blows straight past it
assert r["rss_peak_mb"] < 4096, f"rss {r['rss_peak_mb']} MB"
print("swarm_smoke: overload OK —",
      f"{r['shed_updates']:.0f} shed / {r['accepted_updates']:.0f} accepted,",
      f"{r['steps_completed']} steps, rss {r['rss_peak_mb']:.0f} MB")
EOF
[ $? -ne 0 ] && { echo "swarm_smoke: FAIL — overload verdict" >&2; exit 1; }

grpc=$(run_leg --clients 12 --steps 4 --buffer 6 --think_s 0.02 \
    --backend grpc --procs 2 --ranks_per_port 6 --port 18972 \
    --s2c_delta auto --seed 7 --timeout 200 --run_id swarm-smoke-grpc)
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "swarm_smoke: FAIL — grpc+delta leg exited rc=$rc" >&2
    printf '%s\n' "$grpc" >&2
    exit 1
fi

python - "$grpc" "$light" <<'EOF'
import json
import sys

r = json.loads(sys.argv[1])
light = json.loads(sys.argv[2])
assert r["ok"], r
assert r["backend"] == "GRPC", r
assert r["steps_completed"] == r["steps_requested"], r
# every device-host process finished all its devices (FINISH reached)
assert all(rc == 0 for rc in r["worker_exit_codes"]), r["worker_exit_codes"]
# the delta plane actually engaged over the wire: the server shipped
# delta frames against device-ACKed bases, not just full models
assert r["s2c_delta_frames"] > 0, r
p99_g = r["dispatch_ready_s"]["p99"]
p99_l = light["dispatch_ready_s"]["p99"]
assert p99_g is not None, r
print("swarm_smoke: grpc+delta OK —",
      f"{r['clients']} devices / {len(r['worker_exit_codes'])} procs,",
      f"{r['s2c_delta_frames']:.0f} delta frames,",
      f"p99 dispatch→ready {1e3 * p99_g:.1f}ms",
      f"(loopback leg: {1e3 * p99_l:.1f}ms)")
EOF
[ $? -ne 0 ] && { echo "swarm_smoke: FAIL — grpc+delta verdict" >&2; exit 1; }

wire=$(run_leg --clients 12 --steps 4 --buffer 6 --think_s 0.02 \
    --s2c_delta auto --wire_path device --seed 7 --timeout 180 \
    --run_id swarm-smoke-wire)
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "swarm_smoke: FAIL — device-wire leg exited rc=$rc" >&2
    printf '%s\n' "$wire" >&2
    exit 1
fi

python - "$wire" <<'EOF'
import json
import sys

r = json.loads(sys.argv[1])
assert r["ok"], r
assert r["wire_path"] == "device", r
assert r["steps_completed"] == r["steps_requested"], r
assert r["s2c_delta_frames"] > 0, r
# the device kernels actually served the wire: encodes on the server,
# decodes on every delta-framed dispatch, and never a silent host fallback
assert r["wire_device_encodes"] > 0, r
assert r["wire_device_decodes"] > 0, r
assert r["wire_host_fallbacks"] == 0, r
print("swarm_smoke: device-wire OK —",
      f"{r['clients']} devices, {r['s2c_delta_frames']:.0f} delta frames,",
      f"{r['wire_device_encodes']:.0f} dev encodes /",
      f"{r['wire_device_decodes']:.0f} dev decodes, 0 fallbacks")
EOF
[ $? -ne 0 ] && { echo "swarm_smoke: FAIL — device-wire verdict" >&2; exit 1; }

trace_dir=$(mktemp -d /tmp/swarm_smoke_trace.XXXXXX)
traced=$(run_leg --clients 12 --steps 4 --buffer 6 --think_s 0.02 \
    --backend grpc --procs 2 --ranks_per_port 6 --port 18973 \
    --trace --trace_dir "$trace_dir" --seed 7 --timeout 200 \
    --run_id swarm-smoke-traced)
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "swarm_smoke: FAIL — traced-grpc leg exited rc=$rc" >&2
    printf '%s\n' "$traced" >&2
    rm -rf "$trace_dir"
    exit 1
fi

python - "$traced" <<'EOF'
import json
import sys

r = json.loads(sys.argv[1])
assert r["ok"], r
assert all(rc == 0 for rc in r["worker_exit_codes"]), r["worker_exit_codes"]
assert r["trace_spans"] and r["trace_spans"] > 0, r
assert r["trace_orphans"] == 0, f"orphaned spans: {r['trace_orphans']}"
assert r["critical_path_segments"], r
# every committed round has a walkable critical path
assert r["trace_rounds_with_path"] == r["trace_rounds"] > 0, r
# the trace and the histogram measured the SAME dispatch→ready time
hist_sum = r["dispatch_ready_s"]["sum"]
trace_sum = r["trace_dispatch_ready_s"]
assert hist_sum and hist_sum > 0, r
rel = abs(hist_sum - trace_sum) / hist_sum
assert rel <= 0.05, (
    f"trace/telemetry divergence {100 * rel:.1f}%: "
    f"hist {hist_sum:.4f}s vs trace {trace_sum:.4f}s")
segs = ", ".join(f"{k} {100 * v:.0f}%"
                 for k, v in sorted(r["critical_path_segments"].items(),
                                    key=lambda kv: -kv[1])[:3])
print("swarm_smoke: traced-grpc OK —",
      f"{r['trace_spans']} spans / {r['trace_rounds']} rounds, 0 orphans,",
      f"reconciles within {100 * rel:.1f}%, critical path: {segs}")
EOF
[ $? -ne 0 ] && { echo "swarm_smoke: FAIL — traced-grpc verdict" >&2; rm -rf "$trace_dir"; exit 1; }
rm -rf "$trace_dir"

leak=$(run_leg --clients 32 --steps 24 --buffer 8 --think_s 0.25 \
    --seed 7 --timeout 200 --leak_check --leak_slope_mb_s 1.0 \
    --run_id swarm-smoke-leak)
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "swarm_smoke: FAIL — leak-check leg exited rc=$rc" >&2
    printf '%s\n' "$leak" >&2
    exit 1
fi

python - "$leak" <<'EOF'
import json
import sys

r = json.loads(sys.argv[1])
assert r["ok"], r
assert r["steps_completed"] == r["steps_requested"], r
m = r["mem"]
assert m and m["ok"], m
# the witness measured a real steady state, not a vacuous pass
assert m["rss_slope_mb_per_s"] is not None, m
assert m["rss_slope_mb_per_s"] <= m["rss_slope_limit_mb_per_s"], m
assert m["rss_samples"] >= 8, m
# the mem.* telemetry family actually flowed: the serving plane's bounded
# containers published their occupancy
assert m["containers"], m
assert "server.committed_clients" in m["containers"], m["containers"]
occ = m["containers"]["server.committed_clients"]["occupancy"]
assert occ <= r["clients"], m["containers"]
print("swarm_smoke: leak-check OK —",
      f"slope {m['rss_slope_mb_per_s']:+.3f} MB/s",
      f"(limit {m['rss_slope_limit_mb_per_s']:.1f}),",
      f"rss {m['rss_start_mb']:.0f}→{m['rss_end_mb']:.0f} MB",
      f"over {m['rss_samples']} samples,",
      f"{len(m['containers'])} tracked containers")
EOF
[ $? -ne 0 ] && { echo "swarm_smoke: FAIL — leak-check verdict" >&2; exit 1; }

tiered=$(run_leg --clients 200 --steps 4 --buffer 32 --think_s 0.01 \
    --backend grpc --procs 4 --ranks_per_port 50 --port 18974 \
    --tiers 2 --edges 2 --seed 7 --timeout 220 \
    --run_id swarm-smoke-tiered)
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "swarm_smoke: FAIL — edge-tier leg exited rc=$rc" >&2
    printf '%s\n' "$tiered" >&2
    exit 1
fi

python - "$tiered" <<'EOF'
import json
import sys

r = json.loads(sys.argv[1])
assert r["ok"], r
assert r["backend"] == "GRPC", r
assert r["steps_completed"] == r["steps_requested"], r
assert all(rc == 0 for rc in r["worker_exit_codes"]), r["worker_exit_codes"]
et = r["edge_tier"]
assert et and et["edges"] == 2, et
assert et["edges_finished"] == et["edges"], et
# the root folded ONLY edge summaries: summaries flowed, and not one
# device update reached the root directly
assert et["summaries_folded"] > 0, et
assert et["summary_entries"] > 0, et
assert et["direct_client_updates"] == 0, et
assert et["summary_decode_errors"] == 0, et
# every edge actually carried load (home assignment is contiguous blocks,
# so an idle edge means homing broke)
assert all(pe["folds"] > 0 for pe in et["per_edge"].values()), et["per_edge"]
# the extra tier leaks nothing: edge manager threads must be gone
assert not r["leaked_threads"], r["leaked_threads"]
print("swarm_smoke: edge-tier OK —",
      f"{r['clients']} devices / {et['edges']} edges /",
      f"{len(r['worker_exit_codes'])} procs,",
      f"{et['summaries_folded']:.0f} summaries",
      f"({et['summary_entries']:.0f} entries) folded at root,",
      "0 direct updates, 0 leaked threads")
EOF
[ $? -ne 0 ] && { echo "swarm_smoke: FAIL — edge-tier verdict" >&2; exit 1; }

echo "swarm_smoke: PASS"
