"""Generate the tiny checked-in fixtures for tests/test_real_readers.py.

Each fixture is a minimal but format-faithful instance of the real on-disk
layout the reference consumes (stackoverflow TFF h5 + vocab files, ImageNet
ImageFolder, Landmarks csv+images). Deterministic; a few KB total. Re-run
after changing the formats:  python tools/make_reader_fixtures.py
"""

import json
import os
import zlib

import numpy as np


def _seed(*parts) -> int:
    """Stable cross-process seed (builtin hash() is salted for strings)."""
    return zlib.crc32("/".join(map(str, parts)).encode()) % 2**31

HERE = os.path.dirname(os.path.abspath(__file__))
FIX = os.path.join(HERE, "..", "tests", "fixtures")


def make_stackoverflow():
    import h5py

    d = os.path.join(FIX, "stackoverflow")
    os.makedirs(d, exist_ok=True)
    # vocab: 12 frequent words with fake counts
    words = ["the", "code", "python", "error", "how", "to", "fix", "list",
             "file", "data", "print", "loop"]
    with open(os.path.join(d, "stackoverflow.word_count"), "w") as f:
        for i, w in enumerate(words):
            f.write(f"{w} {1000 - i}\n")
    with open(os.path.join(d, "stackoverflow.tag_count"), "w") as f:
        json.dump({"python": 500, "list": 300, "file": 200, "loop": 100}, f)

    clients = {
        "user_a": {
            "tokens": [b"how to fix the error", b"print the list"],
            "title": [b"fix error", b"the list"],
            "tags": [b"python|list", b"python"],
        },
        "user_b": {
            "tokens": [b"the code zzzunknown data"],
            "title": [b"python"],
            "tags": [b"file|mystery"],
        },
        "user_c": {
            "tokens": [b"loop the loop", b"data file error", b"to print"],
            "title": [b"loop", b"data", b"print"],
            "tags": [b"loop", b"file", b"python|loop"],
        },
    }
    test_clients = {
        "user_t": {
            "tokens": [b"fix the code", b"the data loop"],
            "title": [b"code", b"loop"],
            "tags": [b"python", b"loop"],
        },
    }
    for fname, cc in (("stackoverflow_train.h5", clients),
                      ("stackoverflow_test.h5", test_clients)):
        with h5py.File(os.path.join(d, fname), "w") as h5:
            for cid, g in cc.items():
                grp = h5.create_group(f"examples/{cid}")
                grp.create_dataset("tokens", data=g["tokens"])
                grp.create_dataset("title", data=g["title"])
                grp.create_dataset("tags", data=g["tags"])


def _write_img(path, seed, size=(8, 8)):
    from PIL import Image

    rng = np.random.RandomState(seed)
    arr = rng.randint(0, 255, size + (3,), dtype=np.uint8)
    Image.fromarray(arr).save(path)


def make_imagenet():
    root = os.path.join(FIX, "imagenet", "ILSVRC2012")
    for split, n in (("train", 3), ("val", 2)):
        for ci, cls in enumerate(("n01440764", "n01443537")):
            d = os.path.join(root, split, cls)
            os.makedirs(d, exist_ok=True)
            for i in range(n):
                _write_img(os.path.join(d, f"img_{i}.png"),
                           seed=_seed(split, ci, i))


def make_landmarks():
    root = os.path.join(FIX, "gld")
    os.makedirs(os.path.join(root, "data_user_dict"), exist_ok=True)
    os.makedirs(os.path.join(root, "images"), exist_ok=True)
    rows_train = [
        ("u1", "img001", 0), ("u1", "img002", 1),
        ("u2", "img003", 1), ("u2", "img004", 2), ("u2", "img005", 0),
    ]
    rows_test = [("u1", "img101", 0), ("u2", "img102", 2)]
    for fname, rows in (("gld23k_user_dict_train.csv", rows_train),
                        ("gld23k_user_dict_test.csv", rows_test)):
        with open(os.path.join(root, "data_user_dict", fname), "w") as f:
            f.write("user_id,image_id,class\n")
            for u, im, c in rows:
                f.write(f"{u},{im},{c}\n")
    for _, im, _ in rows_train + rows_test:
        _write_img(os.path.join(root, "images", im + ".jpg"),
                   seed=_seed(im))


def make_coco():
    """Minimal COCO-format detection instance: annotations JSON (sparse
    category ids — exercises the contiguous remapping) + image dirs."""
    import json

    root = os.path.join(FIX, "coco_det", "coco")
    os.makedirs(os.path.join(root, "annotations"), exist_ok=True)

    def blob(split, n_imgs, box_seed):
        rng = np.random.RandomState(_seed("coco", split, box_seed))
        images, annotations = [], []
        os.makedirs(os.path.join(root, split), exist_ok=True)
        aid = 1
        for i in range(n_imgs):
            fname = f"{split}_{i:03d}.jpg"
            _write_img(os.path.join(root, split, fname),
                       seed=_seed("coco", split, i), size=(32, 32))
            images.append({"id": i + 1, "file_name": fname,
                           "width": 32, "height": 32})
            for _ in range(rng.randint(1, 3)):
                w, h = int(rng.randint(6, 16)), int(rng.randint(6, 16))
                x = int(rng.randint(0, 32 - w))
                y = int(rng.randint(0, 32 - h))
                annotations.append({
                    "id": aid, "image_id": i + 1,
                    # sparse ids 1/3/7 → contiguous classes 0/1/2
                    "category_id": int(rng.choice([1, 3, 7])),
                    "bbox": [x, y, w, h], "area": w * h, "iscrowd": 0,
                })
                aid += 1
        return {
            "images": images, "annotations": annotations,
            "categories": [
                {"id": 1, "name": "person"},
                {"id": 3, "name": "car"},
                {"id": 7, "name": "train"},
            ],
        }

    for split, n in (("train2017", 8), ("val2017", 4)):
        with open(os.path.join(
                root, "annotations", f"instances_{split}.json"), "w") as f:
            json.dump(blob(split, n, 1), f)


if __name__ == "__main__":
    make_stackoverflow()
    make_imagenet()
    make_landmarks()
    make_coco()
    total = sum(
        os.path.getsize(os.path.join(r, f))
        for r, _, fs in os.walk(FIX) for f in fs
    )
    print(f"fixtures written to {os.path.normpath(FIX)} ({total} bytes)")
