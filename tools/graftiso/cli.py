"""graftiso CLI: ``python -m tools.graftiso [paths...]``.

Thin suite definition over the shared driver
(:mod:`tools.graftlint.clikit` — flags, baseline handling, rendering, and
the exit-code contract live there, shared with the four sibling suites).
Exit codes: 0 clean (after baseline + pragmas), 1 findings, 2 usage error
OR analyzer crash.

The default (and only) pass is pure AST — graftiso has no runtime/jax
mode: the runtime witness for its I005 contract is the swarm/chaos
thread-leak assertion (docs/graftiso.md), not a trace.
"""

from __future__ import annotations

import argparse
from typing import Dict, List, Optional, Tuple

from ..graftlint import clikit
from ..graftlint.findings import Finding
from .analyzer import DEFAULT_BASELINE_RELPATH, analyze_paths_with_model
from .findings import ISO_RULES


def _analyze(args: argparse.Namespace,
             repo_root: str) -> Tuple[List[Finding], Dict]:
    findings, model = analyze_paths_with_model(args.paths,
                                               repo_root=repo_root)
    extra: Dict = {
        "serving": {
            "classes": sorted(f"{m}.{c}"
                              for m, c in model.serving_classes),
            "closure_size": len(model.closure),
            "singletons": sorted(f"{m}:{n}" for m, n in model.singletons),
            "thread_sites": len(model.thread_sites),
        },
    }
    return findings, extra


def main(argv: Optional[List[str]] = None) -> int:
    return clikit.run_suite(
        argv,
        tool="graftiso",
        description="static state-ownership, tenant-isolation & "
                    "thread-lifecycle verification of the serving plane: "
                    "module-global state in handlers, unscoped singleton "
                    "access, class-level defaults & cross-instance "
                    "aliasing, ambient config, untethered threads",
        rules=ISO_RULES,
        analyze=_analyze,
        baseline_relpath=DEFAULT_BASELINE_RELPATH,
    )


if __name__ == "__main__":
    raise SystemExit(main())
