"""Rule checkers I001–I005 over the :class:`~tools.graftiso.model.ServingModel`.

The I-rules statically enforce the world-scoping contract underneath
multi-tenant serving (docs/graftiso.md):

- **I001** module-global mutable state written from handler/round/worker
  code (the closure) — the direct cross-tenant leak; plus the install-once
  latch prong: a ``global`` rebind anywhere that is not guarded by a
  module-level lock is a racy process-wide latch.
- **I002** process-wide singleton access without a run/world/tenant
  discriminator: direct reads/writes of module instances
  (``telemetry._REG``), written module containers, or class registries
  from closure code — and closure calls into functions whose bodies touch
  one (one resolved hop) — unless the access path carries a scope
  (``self.world.…``, an argument named ``run_id``/``rank``/``world``/…).
- **I003** class-level mutable defaults (one object shared by every
  instance; the guarded-registry idiom — a class-level Lock companion —
  is exempt and policed by I002 instead) and cross-instance mutable-attr
  aliasing from the per-module ownership graph (an attr passed into
  another class's constructor or assigned onto a foreign object escapes
  its owner; world roots are the sanctioned receivers).
- **I004** ambient configuration: module globals captured from
  ``os.environ``/``sys.argv`` at import time, and environment /
  ``get_args()`` reads inside handler/worker code.
- **I005** untethered thread/executor lifecycle: every
  ``threading.Thread``/``Timer``/``ThreadPoolExecutor`` must be joinable
  from its scope's shutdown path — joined/cancelled/shut down in a
  stop/close/finish-reachable method, registered with the world
  (``world.register_thread``/``register_timer``), or ownership-transferred
  (constructor passed directly as an argument / returned to the caller).

Scope notes (documented limits, mirrored in docs/graftiso.md): the
closure stays inside the serving class family plus module-local helpers
(no class-hierarchy guessing); a singleton module's own functions are its
sanctioned accessor API (the call SITE in serving code is what must carry
the scope); transport backends (gRPC/MQTT/loopback) register no handlers
and are policed by graftlint G005/graftproto P-rules instead.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..graftlint.analyzer import (
    Analyzer,
    FuncInfo,
    ModuleInfo,
    _walk_shallow,
    dotted,
)
from .findings import Finding
from .model import (
    MUTATOR_METHODS,
    SHUTDOWN_TOKENS,
    ServingModel,
    Singleton,
    ThreadSite,
    _is_sync_prim,
)

# tokens that mark an access path as scope-discriminated
SCOPE_RECEIVER_TOKENS = ("world", "scope")
SCOPE_ARG_TOKENS = ("world", "run_id", "run", "tenant", "rank", "scope")

# call-name tokens that tether a thread to a scope's lifecycle
REGISTER_TOKENS = ("register_thread", "register_timer")

# ambient-config sources
ENV_PATHS = ("os.environ", "sys.argv")
ENV_CALLS = ("os.getenv", "environ.get", "os.environ.get")
AMBIENT_FNS = ("get_args", "load_arguments")

TETHER_METHODS = {"join", "cancel", "shutdown"}


def _mk(mod: ModuleInfo, rule: str, node: ast.AST, message: str) -> Finding:
    line = getattr(node, "lineno", 1)
    col = getattr(node, "col_offset", 0)
    return Finding(rule=rule, path=mod.rel, line=line, col=col,
                   message=message, line_text=mod.line_text(line))


def _attr_chain(e: ast.expr) -> List[str]:
    """``a.b.c`` → ["a", "b", "c"]; [] when the base is not a Name."""
    parts: List[str] = []
    while isinstance(e, ast.Attribute):
        parts.append(e.attr)
        e = e.value
    if isinstance(e, ast.Name):
        parts.append(e.id)
        return list(reversed(parts))
    return []


def _has_scope_token(e: ast.expr) -> bool:
    for node in ast.walk(e):
        if isinstance(node, ast.Name) and any(
                tok in node.id.lower() for tok in SCOPE_ARG_TOKENS):
            return True
        if isinstance(node, ast.Attribute) and any(
                tok in node.attr.lower() for tok in SCOPE_ARG_TOKENS):
            return True
    return False


def _call_is_scoped(call: ast.Call) -> bool:
    """The call carries a run/world/tenant discriminator: a scoped
    receiver chain (``self.world.…``) or a scope-named argument."""
    chain = _attr_chain(call.func)
    if any(any(tok in seg.lower() for tok in SCOPE_RECEIVER_TOKENS)
           for seg in chain[:-1]):
        return True
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        if _has_scope_token(arg):
            return True
    for kw in call.keywords:
        if kw.arg and any(tok in kw.arg.lower()
                          for tok in SCOPE_ARG_TOKENS):
            return True
    return False


def _function_locals(fi: FuncInfo) -> Set[str]:
    out: Set[str] = set(fi.params())
    for node in _walk_shallow(fi.node):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            for sub in ast.walk(node.target):
                if isinstance(sub, ast.Name):
                    out.add(sub.id)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if item.optional_vars is not None:
                    for sub in ast.walk(item.optional_vars):
                        if isinstance(sub, ast.Name):
                            out.add(sub.id)
    # names declared global are NOT locals
    for node in _walk_shallow(fi.node):
        if isinstance(node, ast.Global):
            out -= set(node.names)
    return out


# ---------------------------------------------------------------------------
# I001 — module-global mutable state written from handler/worker code
# ---------------------------------------------------------------------------


class _I001Checker:
    def __init__(self, model: ServingModel, mod: ModuleInfo, fi: FuncInfo):
        self.model = model
        self.mod = mod
        self.fi = fi
        self.findings: List[Finding] = []
        self.globals_declared: Set[str] = set()
        for node in _walk_shallow(fi.node):
            if isinstance(node, ast.Global):
                self.globals_declared.update(node.names)

    def run(self) -> List[Finding]:
        in_closure = self.fi in self.model.closure
        if in_closure:
            self._check_closure_writes()
        if self.globals_declared and not in_closure:
            self._check_latch_writes()
        return self.findings

    # -- closure prong -------------------------------------------------------

    def _check_closure_writes(self) -> None:
        mutables = self.model.module_mutables.get(self.mod.name, {})
        locals_ = _function_locals(self.fi)

        def module_mutable(name: str) -> bool:
            return name in mutables and name not in locals_

        for node in _walk_shallow(self.fi.node):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Name) and \
                            t.id in self.globals_declared:
                        self.findings.append(_mk(
                            self.mod, "I001", node,
                            f"handler/worker code rebinds module global "
                            f"`{t.id}` — every federation in the process "
                            "shares it; move it onto the world scope"))
                    base = t
                    while isinstance(base, ast.Subscript):
                        base = base.value
                    if (isinstance(t, ast.Subscript)
                            and isinstance(base, ast.Name)
                            and module_mutable(base.id)):
                        self.findings.append(_mk(
                            self.mod, "I001", node,
                            f"handler/worker code writes module-level "
                            f"container `{base.id}` — cross-tenant shared "
                            "state; key it by run identity on the world "
                            "scope"))
            elif isinstance(node, ast.Call):
                f = node.func
                if (isinstance(f, ast.Attribute)
                        and f.attr in MUTATOR_METHODS
                        and isinstance(f.value, ast.Name)
                        and module_mutable(f.value.id)):
                    self.findings.append(_mk(
                        self.mod, "I001", node,
                        f"handler/worker code mutates module-level "
                        f"container `{f.value.id}` via .{f.attr}(...) — "
                        "cross-tenant shared state; move it onto the "
                        "world scope"))

    # -- latch prong ---------------------------------------------------------

    def _check_latch_writes(self) -> None:
        locks = self.model.module_locks.get(self.mod.name, set())

        def visit(node: ast.AST, lock_depth: int) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue
                depth = lock_depth
                if isinstance(child, (ast.With, ast.AsyncWith)):
                    for item in child.items:
                        ctx = item.context_expr
                        name = None
                        if isinstance(ctx, ast.Name):
                            name = ctx.id
                        elif isinstance(ctx, ast.Attribute):
                            name = ctx.attr
                        if name is not None and (
                                name in locks
                                or name.lower().endswith("lock")):
                            depth += 1
                if isinstance(child, (ast.Assign, ast.AugAssign)):
                    targets = (child.targets
                               if isinstance(child, ast.Assign)
                               else [child.target])
                    for t in targets:
                        if (isinstance(t, ast.Name)
                                and t.id in self.globals_declared
                                and lock_depth == 0):
                            self.findings.append(_mk(
                                self.mod, "I001", child,
                                f"`global {t.id}` is rebound without a "
                                "module-level lock held — an install-once "
                                "latch that two threads can both pass; "
                                "wrap the check-and-set in `with _LOCK:`"))
                visit(child, depth)

        visit(self.fi.node, 0)


# ---------------------------------------------------------------------------
# I002 — process-wide singleton access without a scoping key
# ---------------------------------------------------------------------------


class _I002Checker:
    def __init__(self, model: ServingModel, mod: ModuleInfo, fi: FuncInfo):
        self.model = model
        self.mod = mod
        self.fi = fi
        self.findings: List[Finding] = []

    # -- resolution ----------------------------------------------------------

    def _singleton_at(self, mod: ModuleInfo,
                      e: ast.expr) -> Optional[Singleton]:
        """The singleton a Name/Attribute path denotes, if any."""
        chain = _attr_chain(e)
        if not chain:
            return None
        head = chain[0]
        # bare name: same-module singleton or from-import
        if len(chain) == 1:
            s = self.model.singletons.get((mod.name, head))
            if s is not None and s.cls is None:
                return s
            fi = mod.from_imports.get(head)
            if fi:
                return self.model.singletons.get((fi[0], fi[1]))
            return None
        # modalias.NAME
        tgt = mod.imports.get(head)
        if tgt is None and head in mod.from_imports:
            b, orig = mod.from_imports[head]
            full = f"{b}.{orig}" if b else orig
            tgt = full
        if tgt is not None:
            s = self.model.singletons.get((tgt, chain[1]))
            if s is not None and s.cls is None:
                return s
        # ClassName.attr (class registry), local or imported class
        cls_mod: Optional[str] = None
        cls_name = head
        if head in mod.classes:
            cls_mod = mod.name
        else:
            fi2 = mod.from_imports.get(head)
            if fi2:
                cls_mod, cls_name = fi2[0], fi2[1]
        if cls_mod is not None:
            s = self.model.singletons.get(
                (cls_mod, f"{cls_name}.{chain[1]}"))
            if s is not None:
                return s
        # self.attr / cls.attr → registry of the function's own family is
        # sanctioned (its accessor API); other attrs are instance state
        return None

    def _foreign_registry(self, fi: FuncInfo, s: Singleton) -> bool:
        """A class registry accessed from outside its defining family."""
        if s.cls is None:
            return True
        if fi.class_name is None:
            return True
        family = {c for _, c in self.model.family(fi.module.name,
                                                  fi.class_name)}
        return s.cls not in family

    def _body_touches_singleton(self, tf: FuncInfo) -> Optional[Singleton]:
        """A direct singleton access in ``tf``'s body (one resolved hop)."""
        tmod = tf.module
        for node in _walk_shallow(tf.node):
            if isinstance(node, (ast.Attribute, ast.Name)):
                s = self._singleton_at(tmod, node)
                if s is not None and self._foreign_registry(tf, s):
                    return s
            elif isinstance(node, ast.Call):
                # receiver of a method call: _REG.inc(...)
                if isinstance(node.func, ast.Attribute):
                    s = self._singleton_at(tmod, node.func.value)
                    if s is not None and self._foreign_registry(tf, s):
                        return s
        return None

    def _resolve_call(self, call: ast.Call) -> List[FuncInfo]:
        func = call.func
        if isinstance(func, ast.Name):
            return self.model.lint.resolve_name(self.mod, self.fi, func.id)
        if isinstance(func, ast.Attribute) and isinstance(
                func.value, ast.Name):
            base = func.value.id
            tgt = self.mod.imports.get(base)
            if tgt is None and base in self.mod.from_imports:
                b, orig = self.mod.from_imports[base]
                full = f"{b}.{orig}" if b else orig
                tgt = full if full in self.model.modules else None
            if tgt and tgt in self.model.modules:
                target = self.model.modules[tgt]
                if func.attr in target.toplevel:
                    return [target.toplevel[func.attr]]
        return []

    # -- entry ---------------------------------------------------------------

    def run(self) -> List[Finding]:
        if self.fi not in self.model.closure:
            return []
        claimed: Set[int] = set()
        for node in _walk_shallow(self.fi.node):
            if isinstance(node, ast.Call):
                self._check_call(node, claimed)
        for node in _walk_shallow(self.fi.node):
            if isinstance(node, (ast.Attribute, ast.Name)) \
                    and id(node) not in claimed:
                self._check_direct(node, claimed)
        return self.findings

    def _check_call(self, call: ast.Call, claimed: Set[int]) -> None:
        # claim the callee path so the direct pass doesn't re-report it
        for sub in ast.walk(call.func):
            claimed.add(id(sub))
        if _call_is_scoped(call):
            # scoped access: also claim argument paths (the key IS the
            # discriminator)
            for arg in list(call.args) + [kw.value for kw in call.keywords]:
                for sub in ast.walk(arg):
                    claimed.add(id(sub))
            return
        # receiver itself a singleton: _REG.inc(...) — module instances are
        # exempt inside their own module (accessor API), class registries
        # only inside their own class family
        if isinstance(call.func, ast.Attribute):
            s = self._singleton_at(self.mod, call.func.value)
            if s is not None and self._foreign_registry(self.fi, s) \
                    and (s.cls is not None or s.module != self.mod.name):
                self.findings.append(_mk(
                    self.mod, "I002", call,
                    f"handler/worker code calls `.{call.func.attr}(...)` "
                    f"on process-wide singleton `{s.label()}` "
                    f"({s.module}) with no run/world discriminator — "
                    "route it through the world scope"))
                return
        # one resolved hop into a singleton-touching function
        for tf in self._resolve_call(call):
            if tf.class_name is not None or tf.parent is not None:
                continue  # methods/nested fns: covered by closure itself
            s = self._body_touches_singleton(tf)
            if s is not None:
                label = dotted(call.func) or tf.name
                self.findings.append(_mk(
                    self.mod, "I002", call,
                    f"handler/worker code reaches process-wide singleton "
                    f"`{s.label()}` ({s.module}) through `{label}(...)` "
                    "with no run/world discriminator — use the world "
                    "scope (self.world.telemetry.…) or pass the scoping "
                    "key explicitly"))
                return

    def _check_direct(self, node: ast.expr, claimed: Set[int]) -> None:
        s = self._singleton_at(self.mod, node)
        if s is None:
            return
        for sub in ast.walk(node):
            claimed.add(id(sub))
        if s.module == self.mod.name and s.cls is None:
            return  # a module's own functions are its accessor API
        if not self._foreign_registry(self.fi, s):
            return
        self.findings.append(_mk(
            self.mod, "I002", node,
            f"handler/worker code touches process-wide singleton "
            f"`{s.label()}` ({s.module}) directly — cross-tenant state; "
            "access it through a run/world-keyed path"))


# ---------------------------------------------------------------------------
# I003 — class-level mutable defaults + cross-instance aliasing
# ---------------------------------------------------------------------------


def _class_locks(mod: ModuleInfo) -> Dict[str, bool]:
    """class name → has a class-level synchronization primitive."""
    out: Dict[str, bool] = {}
    for clsnode in ast.iter_child_nodes(mod.tree):
        if not isinstance(clsnode, ast.ClassDef):
            continue
        has = False
        for stmt in clsnode.body:
            value = getattr(stmt, "value", None)
            if value is not None and _is_sync_prim(value):
                has = True
        out[clsnode.name] = has
    return out


def check_i003(model: ServingModel, mod: ModuleInfo) -> List[Finding]:
    findings: List[Finding] = []
    locks = _class_locks(mod)
    for key, s in model.singletons.items():
        if key[0] != mod.name or s.cls is None:
            continue
        if locks.get(s.cls):
            # guarded-registry idiom: intentional, lock-companioned —
            # scoped access is I002's business
            continue
        findings.append(_mk(
            mod, "I003", _line_node(s.line),
            f"class-level mutable default `{s.cls}.{s.name}` is ONE "
            "object shared by every instance (and every federation) — "
            "assign it in __init__, or pair it with a class-level Lock "
            "if it is an intentional keyed registry"))
    graph = model.ownership.get(mod.name)
    if graph is not None:
        for e in graph.escapes:
            findings.append(_mk(
                mod, "I003", _line_node(e.line),
                f"mutable attr `{e.cls}.{e.attr}` escapes its owner — "
                f"{e.via}: state written on one instance becomes readable "
                "from another object without passing through the world "
                "scope; hand over a world-owned handle instead"))
    return findings


class _line_node:
    def __init__(self, line: int):
        self.lineno = line
        self.col_offset = 0


# ---------------------------------------------------------------------------
# I004 — ambient-config reads
# ---------------------------------------------------------------------------


def _env_source(mod: ModuleInfo, e: ast.expr) -> Optional[str]:
    for node in ast.walk(e):
        ds = dotted(node) if isinstance(node, (ast.Attribute,
                                               ast.Name)) else None
        if ds in ENV_PATHS:
            return ds
        if isinstance(node, ast.Call):
            cds = dotted(node.func)
            if cds and (cds in ENV_CALLS
                        or any(cds.endswith(c) for c in ENV_CALLS)):
                return cds
    return None


def check_i004_module(model: ServingModel, mod: ModuleInfo) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.iter_child_nodes(mod.tree):
        value = None
        if isinstance(node, ast.Assign):
            value = node.value
        elif isinstance(node, ast.AnnAssign):
            value = node.value
        if value is None:
            continue
        src = _env_source(mod, value)
        if src is not None:
            findings.append(_mk(
                mod, "I004", node,
                f"module global captured from `{src}` at import time — "
                "ambient configuration every tenant in the process "
                "inherits; read it at construction and thread it through "
                "args/the world scope"))
    return findings


def check_i004_closure(model: ServingModel, mod: ModuleInfo,
                       fi: FuncInfo) -> List[Finding]:
    if fi not in model.closure:
        return []
    findings: List[Finding] = []
    env_seen = ambient_seen = False
    for node in _walk_shallow(fi.node):
        if not env_seen and isinstance(node, (ast.Attribute, ast.Subscript,
                                              ast.Call)):
            src = _env_source(mod, node)
            if src is not None:
                env_seen = True
                findings.append(_mk(
                    mod, "I004", node,
                    f"handler/worker code reads `{src}` — ambient config "
                    "inside the serving path; resolve it once at "
                    "construction and carry it on the world scope"))
        if not ambient_seen and isinstance(node, ast.Call):
            ds = dotted(node.func) or ""
            if ds.split(".")[-1] in AMBIENT_FNS:
                ambient_seen = True
                findings.append(_mk(
                    mod, "I004", node,
                    f"handler/worker code calls `{ds}()` — the ambient "
                    "process args are single-tenant by construction; use "
                    "the args/world the manager was built with"))
    return findings


# ---------------------------------------------------------------------------
# I005 — untethered thread/executor lifecycle
# ---------------------------------------------------------------------------


class _I005Checker:
    def __init__(self, model: ServingModel):
        self.model = model
        self.findings: List[Finding] = []
        self._shutdown_cache: Dict[Tuple[str, str], List[FuncInfo]] = {}

    def run(self) -> List[Finding]:
        for site in self.model.thread_sites:
            self._check_site(site)
        return self.findings

    # -- shutdown-path methods ----------------------------------------------

    def _shutdown_methods(self, mod_name: str,
                          cls: str) -> List[FuncInfo]:
        key = (mod_name, cls)
        cached = self._shutdown_cache.get(key)
        if cached is not None:
            return cached
        seeds: List[FuncInfo] = []
        for m, c in self.model.family(mod_name, cls):
            mod = self.model.modules.get(m)
            if mod is None:
                continue
            for name, fi in mod.classes.get(c, {}).items():
                if any(tok in name.lower() for tok in SHUTDOWN_TOKENS):
                    seeds.append(fi)
        out: List[FuncInfo] = []
        seen: Set[FuncInfo] = set()
        work = list(seeds)
        while work:
            fi = work.pop()
            if fi in seen:
                continue
            seen.add(fi)
            out.append(fi)
            for node in _walk_shallow(fi.node):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "self"):
                    t = self.model.family_method(mod_name, cls,
                                                 node.func.attr)
                    if t is not None:
                        work.append(t)
        self._shutdown_cache[key] = out
        return out

    # -- tether predicates ---------------------------------------------------

    @staticmethod
    def _registers(node: ast.Call, ref_pred) -> bool:
        ds = dotted(node.func) or ""
        if not any(tok in ds for tok in REGISTER_TOKENS):
            return False
        return any(ref_pred(a) for a in
                   list(node.args) + [kw.value for kw in node.keywords])

    def _local_tethered(self, site: ThreadSite) -> bool:
        name = site.name

        def is_ref(e: ast.expr) -> bool:
            return isinstance(e, ast.Name) and e.id == name

        for node in _walk_shallow(site.fi.node):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if (isinstance(f, ast.Attribute) and f.attr in TETHER_METHODS
                    and is_ref(f.value)):
                return True
            if self._registers(node, is_ref):
                return True
        # stored onto self or appended into a self container: defer to the
        # attr/container tether analysis
        for node in _walk_shallow(site.fi.node):
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                    for t in node.targets) and is_ref(node.value):
                attr = next(t.attr for t in node.targets
                            if isinstance(t, ast.Attribute))
                return self._attr_tethered(site, attr)
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "append"
                    and isinstance(node.func.value, ast.Attribute)
                    and isinstance(node.func.value.value, ast.Name)
                    and node.func.value.value.id == "self"
                    and any(is_ref(a) for a in node.args)):
                return self._container_tethered(site,
                                                node.func.value.attr)
        return False

    def _attr_tethered(self, site: ThreadSite, attr: str) -> bool:
        fi = site.fi
        if fi.class_name is None:
            return False
        mod_name = fi.module.name

        def attr_ref(e: ast.expr) -> bool:
            return (isinstance(e, ast.Attribute)
                    and isinstance(e.value, ast.Name)
                    and e.value.id == "self" and e.attr == attr)

        # world registration tethers from ANYWHERE in the class family
        for m, c in self.model.family(mod_name, fi.class_name):
            mod = self.model.modules.get(m)
            if mod is None:
                continue
            for method in mod.classes.get(c, {}).values():
                for node in _walk_shallow(method.node):
                    if isinstance(node, ast.Call) and \
                            self._registers(node, attr_ref):
                        return True
        # join/cancel/shutdown must be reachable from the shutdown path
        for method in self._shutdown_methods(mod_name, fi.class_name):
            aliases: Set[str] = set()
            for node in ast.walk(method.node):
                if (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and attr_ref(node.value)):
                    aliases.add(node.targets[0].id)
            for node in ast.walk(method.node):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in TETHER_METHODS):
                    continue
                recv = node.func.value
                if attr_ref(recv):
                    return True
                if isinstance(recv, ast.Name) and recv.id in aliases:
                    return True
        return False

    def _container_tethered(self, site: ThreadSite, attr: str) -> bool:
        """``self.<attr>.append(t)``: tethered when a shutdown-path method
        references the container AND joins/cancels elements."""
        fi = site.fi
        if fi.class_name is None:
            return False
        for method in self._shutdown_methods(fi.module.name,
                                             fi.class_name):
            touches = False
            tethers = False
            for node in ast.walk(method.node):
                if (isinstance(node, ast.Attribute)
                        and isinstance(node.value, ast.Name)
                        and node.value.id == "self"
                        and node.attr == attr):
                    touches = True
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in TETHER_METHODS):
                    tethers = True
            if touches and tethers:
                return True
        return False

    def _comp_tethered(self, site: ThreadSite) -> bool:
        for node in _walk_shallow(site.fi.node):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in TETHER_METHODS):
                return True
        return False

    # -- entry ---------------------------------------------------------------

    def _check_site(self, site: ThreadSite) -> None:
        kind = {"thread": "thread", "timer": "timer",
                "executor": "executor"}[site.kind]
        where = site.fi.qualname
        if site.binding in ("arg", "returned"):
            return  # ownership transferred to the callee / caller
        if site.binding == "chained":
            self.findings.append(_mk(
                site.mod, "I005", site.node,
                f"{kind} started with a chained `.start()` in `{where}` — "
                "no reference survives, so no shutdown path can ever "
                "join/cancel it; bind it and tether it to the scope"))
            return
        if site.binding == "unbound":
            self.findings.append(_mk(
                site.mod, "I005", site.node,
                f"{kind} constructed without a binding in `{where}` — "
                "nothing can join/cancel it; bind it and tether it to "
                "the scope's shutdown path"))
            return
        if site.binding == "local":
            if not self._local_tethered(site):
                self.findings.append(_mk(
                    site.mod, "I005", site.node,
                    f"{kind} `{site.name}` in `{where}` is never joined/"
                    "cancelled or registered with a world scope — it "
                    "outlives the federation that started it; "
                    "world.register_thread/register_timer it or join it "
                    "before returning"))
            return
        if site.binding == "comp":
            if not self._comp_tethered(site):
                self.findings.append(_mk(
                    site.mod, "I005", site.node,
                    f"{kind}s built in comprehension `{site.name}` in "
                    f"`{where}` are never joined — a kill here orphans "
                    "the whole batch; join them (or register each with "
                    "the world scope)"))
            return
        if site.binding == "attr":
            if not self._attr_tethered(site, site.name):
                self.findings.append(_mk(
                    site.mod, "I005", site.node,
                    f"{kind} `self.{site.name}` in `{where}` has no join/"
                    "cancel reachable from a stop/close/finish method and "
                    "no world registration — tenant shutdown would orphan "
                    "it; world.register_thread(self."
                    f"{site.name}) or join it from the scope's shutdown "
                    "path"))


# ---------------------------------------------------------------------------
# Entry
# ---------------------------------------------------------------------------


def check_isolation(modules: Dict[str, ModuleInfo], lint: Analyzer,
                    model: ServingModel) -> List[Finding]:
    findings: List[Finding] = []
    for mod in modules.values():
        findings += check_i003(model, mod)
        findings += check_i004_module(model, mod)
        for fi in mod.funcs_by_node.values():
            findings += _I001Checker(model, mod, fi).run()
            findings += _I002Checker(model, mod, fi).run()
            findings += check_i004_closure(model, mod, fi)
    findings += _I005Checker(model).run()
    return findings
