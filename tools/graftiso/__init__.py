"""graftiso — static state-ownership, tenant-isolation & thread-lifecycle
verification of the serving plane (FIFTH suite on the shared
tools/graftlint/clikit.py driver; docs/graftiso.md)."""

from .analyzer import analyze_paths, analyze_paths_with_model  # noqa: F401
from .findings import ISO_RULES, Finding  # noqa: F401
