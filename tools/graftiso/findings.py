"""graftiso rule registry (I001–I005), merged into the shared graftlint
Finding infrastructure so all five suites render/baseline/JSON identically.

The I-rules statically enforce the serving plane's state-ownership
contract — the precondition for multi-tenant federation serving (ROADMAP
"many worlds, one process, one mesh"): no mutable run state reachable from
a message handler except through an explicitly-scoped world object
(:class:`fedml_tpu.core.world.WorldScope`), and no federation thread whose
lifecycle its own scope cannot end.
"""

from __future__ import annotations

from typing import Dict, Tuple

from ..graftlint.findings import Finding, register_rules

# rule id -> (title, autofix hint)
ISO_RULES: Dict[str, Tuple[str, str]] = {
    "I001": (
        "module-global-state-in-handler",
        "move the state onto the owning object (self.*) or the world scope "
        "(world.*): module globals are shared by every federation in the "
        "process — a handler writing one leaks state across tenants; for a "
        "genuine process-wide latch, guard the write with a module-level "
        "lock (`with _LOCK:`) so the install-once contract is real",
    ),
    "I002": (
        "unscoped-singleton-access",
        "reach process-wide registries only through a run/world/tenant "
        "discriminator: carry the scope on the world object "
        "(self.world.telemetry.counter_inc(...), WorldScope.get(run_id, "
        "rank)) or pass the scoping key in the access itself "
        "(_Broker.get(world), acquire(host, port, rank, q))",
    ),
    "I003": (
        "cross-instance-state-aliasing",
        "class-level mutable defaults are one object shared by every "
        "instance — move them into __init__ (or pair an intentional "
        "registry with a class-level Lock and key all access); never hand "
        "a mutable attr to another object directly — route shared state "
        "through the world scope that owns it",
    ),
    "I004": (
        "ambient-config-read",
        "thread configuration through args at construction time: a module "
        "global captured from the environment at import, or an os.environ/"
        "get_args() read inside a handler, binds every tenant in the "
        "process to one ambient value nobody can scope or replay",
    ),
    "I005": (
        "untethered-thread-lifecycle",
        "tether every thread/timer/executor to its scope's shutdown path: "
        "world.register_thread(t) / world.register_timer(t), or join/"
        "cancel/shutdown it from a stop/close/finish method — an "
        "untethered worker outlives its federation and keeps touching "
        "state the next tenant now owns",
    ),
}

register_rules(ISO_RULES)

__all__ = ["Finding", "ISO_RULES"]
