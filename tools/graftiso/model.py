"""Serving-plane model extraction for the I-rules.

Everything is syntactic (no import of analyzed code), built on graftlint's
module index. Four facts feed the rules:

1. **Serving classes + the handler/worker closure.** A class is *serving*
   when any of its methods registers a message handler
   (``self.register_message_receive_handler(...)``) or a flow callback
   (``add_flow(...)``); its resolvable base classes join the family (the
   ``FedMLCommManager`` base's ``send_message``/``receive_message`` run on
   behalf of every subclass's handlers). The **closure** is the
   reachable-from-a-handler set: registered callbacks, the dispatch entry
   (``receive_message``) and send path (``send_message``), thread/timer
   targets started by serving code, then BFS over ``self.*`` calls
   (family-resolved), module-local calls, nested defs/lambdas, and bare
   ``self._x`` method references scheduled as callbacks. Deliberately NO
   class-hierarchy matching — the closure stays inside the serving family
   plus module helpers, so findings never sprawl into library code.
2. **Process-wide singletons.** Module-level instances
   (``_REG = MetricsRegistry()``; synchronization primitives exempt —
   locks are the guards, not the state), module-level mutable containers
   that some function actually writes (a never-written constant map is
   config, not a registry), and class-level registry containers
   (``_registry: Dict = {}``).
3. **Thread sites.** Every ``threading.Thread``/``Timer``/
   ``ThreadPoolExecutor`` construction with its binding shape (chained
   ``.start()``, local, ``self.attr``, comprehension, argument-owned).
4. **Ownership graph.** Per class: mutable container attrs (assigned
   ``{}``/``[]``/``set()``/… on ``self``) and their *escape edges* — the
   attr passed into another scanned class's constructor or assigned onto
   a foreign object. An attr with no escapes is **dominated** by its
   owner; escaping attrs are I003 findings unless the receiver is a world
   root (class named ``*World*``/``*Scope``).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..graftlint.analyzer import (
    Analyzer,
    FuncInfo,
    ModuleInfo,
    _walk_shallow,
    dotted,
)

REGISTER_CALLS = ("register_message_receive_handler",)
FLOW_CALLS = ("add_flow",)

# synchronization primitives: module-level instances of these are guards,
# not shared state
SYNC_PRIM_CTORS = {
    "Lock", "RLock", "Condition", "Event", "Semaphore", "BoundedSemaphore",
    "Barrier", "local",
}

CONTAINER_CTORS = {"dict", "list", "set", "defaultdict", "OrderedDict",
                   "Counter", "deque"}

MUTATOR_METHODS = {
    "append", "extend", "insert", "pop", "popitem", "clear", "update",
    "setdefault", "remove", "discard", "add",
}

THREAD_CTORS = {"Thread", "Timer"}
EXECUTOR_CTORS = {"ThreadPoolExecutor", "ProcessPoolExecutor"}

# world-root classes: receivers that legitimately take ownership of state
WORLD_ROOT_TOKENS = ("World", "Scope")

# method-name tokens marking a scope's shutdown path
SHUTDOWN_TOKENS = ("stop", "close", "finish", "shutdown", "join", "release",
                   "cancel", "teardown", "exit", "__del__")


def _is_container_value(v: ast.expr) -> bool:
    if isinstance(v, (ast.Dict, ast.List, ast.Set)):
        return True
    if isinstance(v, ast.Call):
        ds = dotted(v.func)
        return bool(ds and ds.split(".")[-1] in CONTAINER_CTORS
                    and not v.args and not v.keywords)
    return False


def _is_sync_prim(v: ast.expr) -> bool:
    if not isinstance(v, ast.Call):
        return False
    ds = dotted(v.func)
    return bool(ds and ds.split(".")[-1] in SYNC_PRIM_CTORS)


def _is_instance_ctor(v: ast.expr) -> bool:
    """``Ctor(...)`` whose last name segment is class-cased."""
    if not isinstance(v, ast.Call):
        return False
    ds = dotted(v.func)
    if not ds:
        return False
    last = ds.split(".")[-1]
    return bool(last[:1].isupper())


@dataclasses.dataclass
class Singleton:
    module: str        # defining module name
    name: str          # module-level (or Class.attr) name
    line: int
    kind: str          # "instance" | "container" | "class-registry"
    cls: Optional[str] = None  # for class registries

    def label(self) -> str:
        return f"{self.cls}.{self.name}" if self.cls else self.name


@dataclasses.dataclass
class ThreadSite:
    mod: ModuleInfo
    fi: FuncInfo
    node: ast.Call
    kind: str          # "thread" | "timer" | "executor"
    binding: str       # "chained" | "local" | "attr" | "comp" | "arg" |
    #                    "returned" | "unbound"
    name: Optional[str] = None  # local name or attr name


@dataclasses.dataclass
class Escape:
    cls: str
    attr: str
    line: int
    via: str           # description of the escape edge
    receiver: str      # receiving class or object expression


class OwnershipGraph:
    """Per-module ownership of mutable attrs: owner class → attrs, plus
    the escape edges that break domination."""

    def __init__(self):
        self.mutable_attrs: Dict[str, Dict[str, int]] = {}  # cls -> attr -> line
        self.escapes: List[Escape] = []

    def dominated(self, cls: str, attr: str) -> bool:
        """True when ``attr`` is a known mutable attr of ``cls`` with no
        escape edge — reachable only through its owner (or a world root)."""
        if attr not in self.mutable_attrs.get(cls, {}):
            return False
        return not any(e.cls == cls and e.attr == attr for e in self.escapes)


class ServingModel:
    def __init__(self, modules: Dict[str, ModuleInfo], lint: Analyzer):
        self.modules = modules
        self.lint = lint
        # (module_name, class_name) of serving classes incl. base families
        self.serving_classes: Set[Tuple[str, str]] = set()
        # class -> resolved base classes (scan-local)
        self._bases: Dict[Tuple[str, str], List[Tuple[str, str]]] = {}
        self.closure: Set[FuncInfo] = set()
        self.singletons: Dict[Tuple[str, str], Singleton] = {}
        self.module_mutables: Dict[str, Dict[str, int]] = {}
        self.module_locks: Dict[str, Set[str]] = {}
        self.thread_sites: List[ThreadSite] = []
        self.ownership: Dict[str, OwnershipGraph] = {}  # module -> graph
        self._build()

    # -- class family --------------------------------------------------------

    def _resolve_base(self, mod: ModuleInfo, base: str
                      ) -> Optional[Tuple[str, str]]:
        parts = base.split(".")
        name = parts[-1]
        if len(parts) == 1:
            if name in mod.classes:
                return (mod.name, name)
            fi = mod.from_imports.get(name)
            if fi:
                return self._follow_export(fi[0], fi[1])
            return None
        head = parts[0]
        tgt = mod.imports.get(head)
        if tgt and tgt in self.modules:
            return self._follow_export(tgt, name)
        return None

    def _follow_export(self, mod_name: str, cls: str,
                       hops: int = 3) -> Optional[Tuple[str, str]]:
        """Resolve (module, class) through package re-export chains
        (``from .comm_manager import FedMLCommManager`` in __init__).
        When the chain leaves the scanned set (partial scans skip the
        package __init__), fall back to a unique-name match over the
        loaded modules."""
        for _ in range(hops):
            target = self.modules.get(mod_name)
            if target is None:
                break
            if cls in target.classes:
                return (target.name, cls)
            fi = target.from_imports.get(cls)
            if fi is None:
                return None
            mod_name, cls = fi
        owners = [m.name for m in self.modules.values() if cls in m.classes]
        if len(owners) == 1:
            return (owners[0], cls)
        return None

    def family(self, mod_name: str, cls: str) -> List[Tuple[str, str]]:
        """The class plus its resolvable ancestors (scan-local), MRO-ish."""
        out: List[Tuple[str, str]] = []
        seen: Set[Tuple[str, str]] = set()
        work = [(mod_name, cls)]
        while work:
            key = work.pop(0)
            if key in seen:
                continue
            seen.add(key)
            out.append(key)
            mod = self.modules.get(key[0])
            if mod is None:
                continue
            for b in mod.class_bases.get(key[1], []):
                rb = self._resolve_base(mod, b)
                if rb is not None:
                    work.append(rb)
        return out

    def family_method(self, mod_name: str, cls: str,
                      name: str) -> Optional[FuncInfo]:
        for m, c in self.family(mod_name, cls):
            mod = self.modules.get(m)
            if mod is None:
                continue
            fi = mod.classes.get(c, {}).get(name)
            if fi is not None:
                return fi
        return None

    def is_serving(self, fi: FuncInfo) -> bool:
        return (fi.class_name is not None
                and (fi.module.name, fi.class_name) in self.serving_classes)

    # -- build ---------------------------------------------------------------

    def _build(self) -> None:
        self._find_serving_classes()
        self._find_singletons()
        self._find_thread_sites()
        self._build_closure()
        self._build_ownership()

    def _find_serving_classes(self) -> None:
        direct: Set[Tuple[str, str]] = set()
        for mod in self.modules.values():
            for cls, methods in mod.classes.items():
                for fi in methods.values():
                    for node in _walk_shallow(fi.node):
                        if not isinstance(node, ast.Call):
                            continue
                        ds = dotted(node.func) or ""
                        tail = ds.split(".")[-1]
                        if tail in REGISTER_CALLS or tail in FLOW_CALLS:
                            direct.add((mod.name, cls))
        for key in direct:
            for fam in self.family(*key):
                self.serving_classes.add(fam)

    # -- singletons ----------------------------------------------------------

    def _find_singletons(self) -> None:
        for mod in self.modules.values():
            locks: Set[str] = set()
            containers: Dict[str, int] = {}
            for node in ast.iter_child_nodes(mod.tree):
                targets: List[ast.expr] = []
                value: Optional[ast.expr] = None
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    targets, value = [node.target], node.value
                if value is None:
                    continue
                for t in targets:
                    if not isinstance(t, ast.Name):
                        continue
                    name = t.id
                    if _is_sync_prim(value):
                        locks.add(name)
                        continue
                    if _is_container_value(value):
                        containers[name] = node.lineno
                        continue
                    if ((name.startswith("_") or name.isupper())
                            and _is_instance_ctor(value)):
                        self.singletons[(mod.name, name)] = Singleton(
                            mod.name, name, node.lineno, "instance")
            self.module_locks[mod.name] = locks
            # a module container is a singleton only when some function
            # body actually WRITES it (a registry/cache); constant lookup
            # tables stay out
            written = self._written_module_names(mod)
            self.module_mutables[mod.name] = dict(containers)
            for name, line in containers.items():
                if name in written:
                    self.singletons[(mod.name, name)] = Singleton(
                        mod.name, name, line, "container")
            # class-level registries
            for clsnode in ast.iter_child_nodes(mod.tree):
                if not isinstance(clsnode, ast.ClassDef):
                    continue
                for stmt in clsnode.body:
                    tgt, val = None, None
                    if (isinstance(stmt, ast.Assign)
                            and len(stmt.targets) == 1
                            and isinstance(stmt.targets[0], ast.Name)):
                        tgt, val = stmt.targets[0].id, stmt.value
                    elif (isinstance(stmt, ast.AnnAssign)
                          and isinstance(stmt.target, ast.Name)
                          and stmt.value is not None):
                        tgt, val = stmt.target.id, stmt.value
                    if tgt is None or val is None:
                        continue
                    if _is_container_value(val):
                        self.singletons[(mod.name, f"{clsnode.name}.{tgt}")] \
                            = Singleton(mod.name, tgt, stmt.lineno,
                                        "class-registry", cls=clsnode.name)

    def _written_module_names(self, mod: ModuleInfo) -> Set[str]:
        written: Set[str] = set()
        for fi in mod.funcs_by_node.values():
            for node in _walk_shallow(fi.node):
                if isinstance(node, ast.Global):
                    written.update(node.names)
                elif isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = (node.targets if isinstance(node, ast.Assign)
                               else [node.target])
                    for t in targets:
                        base = t
                        while isinstance(base, ast.Subscript):
                            base = base.value
                        if isinstance(base, ast.Name):
                            if isinstance(t, ast.Subscript):
                                written.add(base.id)
                elif isinstance(node, ast.Call):
                    f = node.func
                    if (isinstance(f, ast.Attribute)
                            and f.attr in MUTATOR_METHODS
                            and isinstance(f.value, ast.Name)):
                        written.add(f.value.id)
                elif isinstance(node, ast.Delete):
                    for t in node.targets:
                        if isinstance(t, ast.Subscript) and isinstance(
                                t.value, ast.Name):
                            written.add(t.value.id)
        return written

    # -- thread sites --------------------------------------------------------

    def _thread_kind(self, mod: ModuleInfo, call: ast.Call) -> Optional[str]:
        ds = dotted(call.func)
        if not ds:
            return None
        parts = ds.split(".")
        last = parts[-1]
        if last in THREAD_CTORS:
            ok = False
            if len(parts) > 1:
                head = parts[0]
                ok = (head == "threading"
                      or mod.imports.get(head, "") == "threading")
            else:
                fi = mod.from_imports.get(last)
                ok = bool(fi and fi[0] == "threading")
            if ok:
                return "timer" if last == "Timer" else "thread"
            return None
        if last in EXECUTOR_CTORS:
            return "executor"
        return None

    def _find_thread_sites(self) -> None:
        for mod in self.modules.values():
            for fi in mod.funcs_by_node.values():
                self._scan_thread_sites(mod, fi)

    def _scan_thread_sites(self, mod: ModuleInfo, fi: FuncInfo) -> None:
        claimed: Set[int] = set()

        def record(call: ast.Call, kind: str, binding: str,
                   name: Optional[str] = None) -> None:
            if id(call) in claimed:
                return
            claimed.add(id(call))
            self.thread_sites.append(
                ThreadSite(mod, fi, call, kind, binding, name))

        for node in _walk_shallow(fi.node):
            # bindings first, so the generic pass below sees them claimed
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t, v = node.targets[0], node.value
                kind = isinstance(v, ast.Call) and self._thread_kind(mod, v)
                if kind:
                    if isinstance(t, ast.Name):
                        record(v, kind, "local", t.id)
                    elif (isinstance(t, ast.Attribute)
                          and isinstance(t.value, ast.Name)
                          and t.value.id == "self"):
                        record(v, kind, "attr", t.attr)
                    continue
                if isinstance(v, (ast.ListComp, ast.GeneratorExp)) \
                        and isinstance(t, ast.Name):
                    for sub in ast.walk(v):
                        if isinstance(sub, ast.Call):
                            k = self._thread_kind(mod, sub)
                            if k:
                                record(sub, k, "comp", t.id)
            elif isinstance(node, ast.Return) and isinstance(
                    node.value, ast.Call):
                k = self._thread_kind(mod, node.value)
                if k:
                    record(node.value, k, "returned")
            elif isinstance(node, ast.Call):
                # Thread(...).start() chained — never joinable
                f = node.func
                if (isinstance(f, ast.Attribute) and f.attr == "start"
                        and isinstance(f.value, ast.Call)):
                    k = self._thread_kind(mod, f.value)
                    if k:
                        record(f.value, k, "chained")
                # ctor directly as an argument: ownership transferred
                for arg in list(node.args) + [kw.value for kw in
                                              node.keywords]:
                    if isinstance(arg, ast.Call):
                        k = self._thread_kind(mod, arg)
                        if k:
                            record(arg, k, "arg")
        # anything not claimed by a shape above
        for node in _walk_shallow(fi.node):
            if isinstance(node, ast.Call):
                k = self._thread_kind(mod, node)
                if k and id(node) not in claimed:
                    record(node, k, "unbound")

    # -- closure -------------------------------------------------------------

    def _callback_target(self, fi: FuncInfo,
                         expr: ast.expr) -> Optional[FuncInfo]:
        """Resolve a callback expression (self._x, bare name, lambda)."""
        mod = fi.module
        if isinstance(expr, ast.Attribute) and isinstance(
                expr.value, ast.Name) and expr.value.id == "self":
            if fi.class_name:
                return self.family_method(mod.name, fi.class_name, expr.attr)
            return None
        if isinstance(expr, ast.Name):
            targets = self.lint.resolve_name(mod, fi, expr.id)
            return targets[0] if targets else None
        if isinstance(expr, ast.Lambda):
            return mod.funcs_by_node.get(id(expr))
        return None

    def _build_closure(self) -> None:
        roots: List[FuncInfo] = []
        for mod in self.modules.values():
            for cls, methods in mod.classes.items():
                if (mod.name, cls) not in self.serving_classes:
                    continue
                for mname in ("receive_message", "send_message"):
                    fi = methods.get(mname)
                    if fi is not None:
                        roots.append(fi)
                for fi in methods.values():
                    for node in _walk_shallow(fi.node):
                        if not isinstance(node, ast.Call):
                            continue
                        ds = dotted(node.func) or ""
                        tail = ds.split(".")[-1]
                        if tail in REGISTER_CALLS and len(node.args) >= 2:
                            t = self._callback_target(fi, node.args[1])
                            if t is not None:
                                roots.append(t)
                        elif tail in FLOW_CALLS:
                            cb = None
                            if len(node.args) >= 2:
                                cb = node.args[1]
                            for kw in node.keywords:
                                if kw.arg in ("callback", "executor_task"):
                                    cb = kw.value
                            if cb is not None:
                                t = self._callback_target(fi, cb)
                                if t is not None:
                                    roots.append(t)
                        else:
                            # worker roots: thread/timer targets started
                            # from serving code
                            kind = self._thread_kind(mod, node)
                            if kind:
                                for kw in node.keywords:
                                    if kw.arg == "target":
                                        t = self._callback_target(
                                            fi, kw.value)
                                        if t is not None:
                                            roots.append(t)
                                if kind == "timer" and len(node.args) >= 2:
                                    t = self._callback_target(
                                        fi, node.args[1])
                                    if t is not None:
                                        roots.append(t)
        work = list(roots)
        while work:
            fi = work.pop()
            if fi in self.closure:
                continue
            self.closure.add(fi)
            work.extend(self._closure_edges(fi))

    def _closure_edges(self, fi: FuncInfo) -> List[FuncInfo]:
        mod = fi.module
        out: List[FuncInfo] = []
        out.extend(fi.nested.values())
        for node in _walk_shallow(fi.node):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            # self.method(...) — family-resolved (covers base classes)
            if (isinstance(func, ast.Attribute)
                    and isinstance(func.value, ast.Name)
                    and func.value.id == "self" and fi.class_name):
                t = self.family_method(mod.name, fi.class_name, func.attr)
                if t is not None:
                    out.append(t)
            elif isinstance(func, ast.Name):
                out.extend(self.lint.resolve_name(mod, fi, func.id))
            elif isinstance(func, ast.Attribute) and isinstance(
                    func.value, ast.Name):
                # module-qualified package call: modalias.fn(...)
                base = func.value.id
                tgt = mod.imports.get(base)
                if tgt is None and base in mod.from_imports:
                    b, orig = mod.from_imports[base]
                    full = f"{b}.{orig}" if b else orig
                    tgt = full if full in self.modules else None
                if tgt and tgt in self.modules:
                    target = self.modules[tgt]
                    if func.attr in target.toplevel:
                        out.append(target.toplevel[func.attr])
            # scheduled callbacks: bare self._x / lambda passed as an arg
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Lambda):
                    t = mod.funcs_by_node.get(id(arg))
                    if t is not None:
                        out.append(t)
                elif (isinstance(arg, ast.Attribute)
                      and isinstance(arg.value, ast.Name)
                      and arg.value.id == "self" and fi.class_name):
                    t = self.family_method(mod.name, fi.class_name, arg.attr)
                    if t is not None:
                        out.append(t)
        return out

    # -- ownership graph -----------------------------------------------------

    def _build_ownership(self) -> None:
        for mod in self.modules.values():
            graph = OwnershipGraph()
            self.ownership[mod.name] = graph
            for cls, methods in mod.classes.items():
                attrs: Dict[str, int] = {}
                for fi in methods.values():
                    for node in _walk_shallow(fi.node):
                        if (isinstance(node, ast.Assign)
                                and len(node.targets) == 1):
                            t = node.targets[0]
                            if (isinstance(t, ast.Attribute)
                                    and isinstance(t.value, ast.Name)
                                    and t.value.id == "self"
                                    and _is_container_value(node.value)):
                                attrs.setdefault(t.attr, node.lineno)
                if attrs:
                    graph.mutable_attrs[cls] = attrs
            for cls, methods in mod.classes.items():
                attrs = graph.mutable_attrs.get(cls, {})
                if not attrs:
                    continue
                for fi in methods.values():
                    self._scan_escapes(mod, cls, fi, attrs, graph)

    def _is_scanned_class_ctor(self, mod: ModuleInfo,
                               call: ast.Call) -> Optional[str]:
        if not isinstance(call.func, ast.Name):
            return None
        name = call.func.id
        if name in mod.classes:
            return name
        fi = mod.from_imports.get(name)
        if fi:
            target = self.modules.get(fi[0])
            if target and fi[1] in target.classes:
                return fi[1]
        return None

    def _scan_escapes(self, mod: ModuleInfo, cls: str, fi: FuncInfo,
                      attrs: Dict[str, int], graph: OwnershipGraph) -> None:
        def self_attr(e: ast.expr) -> Optional[str]:
            if (isinstance(e, ast.Attribute)
                    and isinstance(e.value, ast.Name)
                    and e.value.id == "self" and e.attr in attrs):
                return e.attr
            return None

        for node in _walk_shallow(fi.node):
            if isinstance(node, ast.Call):
                target_cls = self._is_scanned_class_ctor(mod, node)
                if target_cls is None:
                    continue
                if any(tok in target_cls for tok in WORLD_ROOT_TOKENS):
                    continue  # the world root is the sanctioned owner
                for arg in list(node.args) + [kw.value
                                              for kw in node.keywords]:
                    a = self_attr(arg)
                    if a is not None:
                        graph.escapes.append(Escape(
                            cls, a, node.lineno,
                            f"passed into {target_cls}(...)", target_cls))
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                a = self_attr(node.value)
                if (a is not None and isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id not in ("self", "cls")
                        and not any(tok.lower() in t.value.id.lower()
                                    for tok in WORLD_ROOT_TOKENS)):
                    graph.escapes.append(Escape(
                        cls, a, node.lineno,
                        f"assigned onto {t.value.id}.{t.attr}", t.value.id))


def build_model(modules: Dict[str, ModuleInfo],
                lint: Analyzer) -> ServingModel:
    return ServingModel(modules, lint)
