"""Measure the reference's sp FedAvg throughput on THIS machine.

Drives the reference's own single-process FedAvg loop
(`/root/reference/python/fedml/simulation/sp/fedavg/fedavg_api.py:65-123`)
with its own torch ResNet-56 (`model/cv/resnet.py:257`) and its own
`ModelTrainerCLS` on synthetic CIFAR-10-shaped data, matching the config of
`bench.py` (100 clients, 10/round, 1 local epoch, batch 32, 500 samples per
client). torch has no TPU backend, so this runs on CPU — the reference's only
available substrate here. The measured rounds/sec becomes bench.py's
REF_ROUNDS_PER_SEC.

Missing optional deps of the reference (wandb, paho, boto3, ...) are stubbed
with MagicMock modules — none of them are on the measured hot path (the hot
loop is pure torch: client batches + state-dict aggregation).

Usage:  python tools/measure_ref_baseline.py [--rounds N]
Prints one JSON line: {"ref_rounds_per_sec": ..., "rounds": N, "secs": ...}
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
import time
import types
from unittest import mock

REF = "/root/reference/python"


def _import_with_stubs(name: str, max_stubs: int = 60):
    """Import `name`, stubbing any missing third-party modules."""
    stubbed = []
    for _ in range(max_stubs):
        try:
            return __import__(name, fromlist=["_"]), stubbed
        except ModuleNotFoundError as e:
            missing = e.name
            if missing is None or missing in sys.modules:
                raise
            stub = mock.MagicMock(name=f"stub:{missing}")
            stub.__spec__ = types.SimpleNamespace(name=missing)
            stub.__path__ = []
            sys.modules[missing] = stub
            stubbed.append(missing)
    raise RuntimeError(f"too many missing modules stubbed: {stubbed}")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--clients-total", type=int, default=100)
    ap.add_argument("--clients-per-round", type=int, default=10)
    ap.add_argument("--samples-per-client", type=int, default=500)
    ap.add_argument("--batch-size", type=int, default=32)
    args_ns = ap.parse_args()

    sys.path.insert(0, REF)
    logging.disable(logging.INFO)  # the reference logs every batch

    import numpy as np
    import torch

    torch.manual_seed(0)

    _import_with_stubs("fedml")
    from fedml.model.cv.resnet import resnet56
    from fedml.simulation.sp.fedavg.fedavg_api import FedAvgAPI

    n_total = args_ns.clients_total
    per_client = args_ns.samples_per_client

    # synthetic CIFAR-shaped shards, one TensorDataset loader per client
    def make_loader(n, seed):
        g = torch.Generator().manual_seed(seed)
        x = torch.randn(n, 3, 32, 32, generator=g)
        y = torch.randint(0, 10, (n,), generator=g)
        return torch.utils.data.DataLoader(
            torch.utils.data.TensorDataset(x, y),
            batch_size=args_ns.batch_size, shuffle=False,
        )

    train_local = {i: make_loader(per_client, i) for i in range(n_total)}
    test_local = {i: make_loader(64, 10_000 + i) for i in range(n_total)}
    train_num = {i: per_client for i in range(n_total)}
    dataset = [
        n_total * per_client, n_total * 64, None, None,
        train_num, train_local, test_local, 10,
    ]

    ref_args = argparse.Namespace(
        dataset="cifar10", model="resnet56",
        client_num_in_total=n_total,
        client_num_per_round=args_ns.clients_per_round,
        comm_round=args_ns.rounds, epochs=1,
        batch_size=args_ns.batch_size, learning_rate=0.1,
        client_optimizer="sgd", weight_decay=0.0,
        frequency_of_the_test=100_000, enable_wandb=False,
    )

    model = resnet56(class_num=10)
    api = FedAvgAPI(ref_args, torch.device("cpu"), dataset, model)

    # eval is not part of the per-round cost in either framework's bench
    api._local_test_on_all_clients = lambda *_a, **_k: None

    # warmup: 1 round (thread pools, allocator)
    ref_args.comm_round = 1
    api.args = ref_args
    t = time.perf_counter()
    api.train()
    warm = time.perf_counter() - t

    ref_args.comm_round = args_ns.rounds
    t0 = time.perf_counter()
    api.train()
    dt = time.perf_counter() - t0

    out = {
        "ref_rounds_per_sec": round(args_ns.rounds / dt, 5),
        "rounds": args_ns.rounds,
        "secs": round(dt, 2),
        "warmup_round_secs": round(warm, 2),
        "config": "100c/10pr/500spc/bs32/1ep resnet56 cifar10-shaped, torch CPU",
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
